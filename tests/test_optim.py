"""Large-batch playbook (arXiv:1909.09756): the optimizer registry
(sgd/momentum/LARS/LAMB), gradient accumulation, and fp32-master-weight
bf16 training — each verified against the replicated baseline per the
ZeRO-1 parity methodology (PR 6), plus the warmup/polynomial schedule
and the typed config validation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import base_config
from distributedmnist_tpu.core.config import ConfigError, OptimConfig
from distributedmnist_tpu.data.datasets import make_synthetic
from distributedmnist_tpu.models.registry import get_model
from distributedmnist_tpu.parallel.api import (build_train_step,
                                               canonical_save_state,
                                               init_train_state,
                                               state_partition_specs,
                                               zero1_plan_for)
from distributedmnist_tpu.train import checkpoint as ckpt
from distributedmnist_tpu.train import optim
from distributedmnist_tpu.train.loop import Trainer
from distributedmnist_tpu.train.lr_schedule import (constant,
                                                    warmup_polynomial_decay)

LR = 0.05


def _cfg(**over):
    base = {"model": {"dropout_rate": 0.0}}
    for k, v in over.items():
        if isinstance(v, dict) and k in base:
            base[k].update(v)
        else:
            base[k] = v
    return base_config(**base)


def _run_steps(cfg, topo, batch, steps=4):
    model = get_model(cfg.model)
    state = topo.device_put_state(init_train_state(model, cfg, topo),
                                  state_partition_specs(model, cfg, topo))
    step_fn = build_train_step(model, cfg, topo, constant(LR))
    gbatch = topo.device_put_batch(batch)
    hist = []
    for _ in range(steps):
        state, m = step_fn(state, gbatch)
        hist.append(m)
    return state, hist


@pytest.fixture(scope="module")
def batch64():
    ds = make_synthetic(num_train=128, num_test=16)
    return {"image": ds.train.images[:64], "label": ds.train.labels[:64]}


# ---------------------------------------------------------------------------
# config validation + schedule (satellite)
# ---------------------------------------------------------------------------

@pytest.mark.tier1
def test_unknown_optimizer_is_typed_error():
    with pytest.raises(ConfigError, match=r"lamb"):  # names the valid set
        optim.make_optimizer(OptimConfig(name="adamw"))


@pytest.mark.tier1
@pytest.mark.parametrize("name", ["lars", "lamb"])
def test_trust_ratio_optimizers_reject_momentum_knob(name):
    with pytest.raises(ConfigError, match="own their momentum"):
        optim.make_optimizer(OptimConfig(name=name, momentum=0.9))
    optim.make_optimizer(OptimConfig(name=name))  # momentum=0 is fine


@pytest.mark.tier1
def test_unknown_schedule_is_typed_error():
    with pytest.raises(ConfigError, match="schedule"):
        optim.make_optimizer(OptimConfig(schedule="cosine"))


@pytest.mark.tier1
def test_opt_state_kind():
    assert optim.opt_state_kind(OptimConfig()) == "none"
    assert optim.opt_state_kind(OptimConfig(momentum=0.9)) == "momentum"
    assert optim.opt_state_kind(
        OptimConfig(name="momentum", momentum=0.9)) == "momentum"
    assert optim.opt_state_kind(OptimConfig(name="lars")) == "lars"
    assert optim.opt_state_kind(OptimConfig(name="lamb")) == "lamb"
    # heavyball at 0 is exactly plain sgd — naming it 'momentum' is a
    # typed config error, not a silent sgd run with a dead slot
    with pytest.raises(ConfigError, match="positive"):
        optim.opt_state_kind(OptimConfig(name="momentum"))
    # and the typed dtype validation for the precision section
    from distributedmnist_tpu.parallel.api import resolved_param_dtype
    from distributedmnist_tpu.core.config import ExperimentConfig
    with pytest.raises(ConfigError, match="bf16"):
        resolved_param_dtype(ExperimentConfig.from_dict(
            {"precision": {"param_dtype": "bf16"}}))
    with pytest.raises(ConfigError, match="floating"):
        resolved_param_dtype(ExperimentConfig.from_dict(
            {"precision": {"param_dtype": "int32"}}))


@pytest.mark.tier1
def test_warmup_polynomial_schedule_values():
    s = warmup_polynomial_decay(1.0, warmup_steps=10, total_steps=110,
                                end_lr=0.1, power=2.0)
    # linear ramp: update t applies (t+1)/warmup · base
    np.testing.assert_allclose(float(s(jnp.int32(0))), 0.1, rtol=1e-6)
    np.testing.assert_allclose(float(s(jnp.int32(4))), 0.5, rtol=1e-6)
    # end of warmup hits base
    np.testing.assert_allclose(float(s(jnp.int32(10))), 1.0, rtol=1e-6)
    # halfway through decay: end + (base-end)·(1-0.5)^2
    np.testing.assert_allclose(float(s(jnp.int32(60))),
                               0.1 + 0.9 * 0.25, rtol=1e-6)
    # at/after total_steps: holds at end_lr
    np.testing.assert_allclose(float(s(jnp.int32(110))), 0.1, rtol=1e-6)
    np.testing.assert_allclose(float(s(jnp.int32(500))), 0.1, rtol=1e-6)
    with pytest.raises(ValueError):
        warmup_polynomial_decay(1.0, warmup_steps=20, total_steps=10)


# ---------------------------------------------------------------------------
# per-leaf update rules vs straight-line numpy references
# ---------------------------------------------------------------------------

@pytest.mark.tier1
def test_lars_leaf_matches_reference_math():
    ocfg = OptimConfig(name="lars", beta1=0.9, trust_coefficient=0.001,
                       weight_decay=0.01)
    opt = optim.make_optimizer(ocfg)
    rng = np.random.default_rng(0)
    p = rng.standard_normal((4, 5)).astype(np.float32)
    g = rng.standard_normal((4, 5)).astype(np.float32)
    b = rng.standard_normal((4, 5)).astype(np.float32)
    lr = 0.1
    new_p, (nb,) = opt.update_leaf(jnp.asarray(p), jnp.asarray(g),
                                   (jnp.asarray(b),), lr,
                                   jnp.float32(1.0), lambda x: x, True)
    gw = g + 0.01 * p
    trust = 0.001 * np.linalg.norm(p) / np.linalg.norm(gw)
    want_b = 0.9 * b + trust * gw
    want_p = p - lr * want_b
    np.testing.assert_allclose(np.asarray(nb), want_b, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(new_p), want_p, rtol=1e-5,
                               atol=1e-6)
    # 1-D leaves skip decay + trust (adapt=False)
    p1, g1, b1 = p[0], g[0], b[0]
    new_p1, (nb1,) = opt.update_leaf(jnp.asarray(p1), jnp.asarray(g1),
                                     (jnp.asarray(b1),), lr,
                                     jnp.float32(1.0), lambda x: x, False)
    np.testing.assert_allclose(np.asarray(nb1), 0.9 * b1 + g1, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(new_p1),
                               p1 - lr * (0.9 * b1 + g1), rtol=1e-5,
                               atol=1e-6)


@pytest.mark.tier1
def test_lamb_leaf_matches_reference_math():
    ocfg = OptimConfig(name="lamb", beta1=0.9, beta2=0.99, eps=1e-6,
                       weight_decay=0.01)
    opt = optim.make_optimizer(ocfg)
    rng = np.random.default_rng(1)
    p = rng.standard_normal((3, 7)).astype(np.float32)
    g = rng.standard_normal((3, 7)).astype(np.float32)
    m = rng.standard_normal((3, 7)).astype(np.float32) * 0.1
    v = np.abs(rng.standard_normal((3, 7))).astype(np.float32) * 0.01
    lr, t = 0.1, 3.0
    new_p, (nm, nv) = opt.update_leaf(
        jnp.asarray(p), jnp.asarray(g), (jnp.asarray(m), jnp.asarray(v)),
        lr, jnp.float32(t), lambda x: x, True)
    want_m = 0.9 * m + 0.1 * g
    want_v = 0.99 * v + 0.01 * g * g
    m_hat = want_m / (1 - 0.9 ** t)
    v_hat = want_v / (1 - 0.99 ** t)
    u = m_hat / (np.sqrt(v_hat) + 1e-6) + 0.01 * p
    ratio = np.linalg.norm(p) / np.linalg.norm(u)
    want_p = p - lr * ratio * u
    np.testing.assert_allclose(np.asarray(nm), want_m, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(nv), want_v, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(new_p), want_p, rtol=1e-4,
                               atol=1e-5)


# ---------------------------------------------------------------------------
# ZeRO-1 parity: trust-ratio optimizers under the sharded weight update
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", ["lars", "lamb"])
def test_trust_ratio_zero1_matches_replicated(topo8, batch64, name):
    """The per-leaf + norm_reduce factoring is exactly what makes
    LARS/LAMB thread through ZeRO-1: chunked norms complete over the
    replica axis and must reproduce the replicated update.

    Tolerance/step-count note: unlike the linear momentum update
    (bitwise across the knob, test_zero1), the trust ratio DIVIDES two
    norms whose chunked (psum-of-chunk-sums) and full-leaf reductions
    reassociate; the per-step discrepancy is float-epsilon (measured
    1.5e-8 params / 2e-10 slots after step 1) but it compounds
    CHAOTICALLY through the training dynamics (2.5e-3 by step 4 at
    lr=0.05 — same seed, same data). The gate is therefore tight
    parity over 2 steps — enough to cover the moment accumulation and
    a second trust-ratio application on diverged-state inputs — not a
    loose tolerance over a longer run that would hide a genuinely
    missing reduction. LAMB gets extra slack: its ``1/(sqrt(v)+eps)``
    is signSGD-like while v is still near zero, so epsilon-level
    moment noise moves whole update elements (measured 2.3e-5 on a
    bias leaf at step 2); a missing reduction would be O(1)."""
    tol = (dict(rtol=5e-4, atol=1e-4) if name == "lamb"
           else dict(rtol=1e-5, atol=1e-6))
    over = {"optim": {"name": name, "initial_learning_rate": LR,
                      "weight_decay": 1e-3}}
    st_r, hist_r = _run_steps(_cfg(parallel={"shard_weight_update": False},
                                   **over), topo8, batch64, steps=2)
    st_s, hist_s = _run_steps(_cfg(parallel={"shard_weight_update": True},
                                   **over), topo8, batch64, steps=2)
    for mr, ms in zip(hist_r, hist_s):
        np.testing.assert_allclose(float(ms["loss"]), float(mr["loss"]),
                                   rtol=1e-5, atol=1e-5)
    for a, b in zip(jax.tree.leaves(jax.device_get(st_s.params)),
                    jax.tree.leaves(jax.device_get(st_r.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), **tol)
    # sharded slots unpack to the replicated buffers
    cfg_s = _cfg(parallel={"shard_weight_update": True}, **over)
    plan = zero1_plan_for(get_model(cfg_s.model), cfg_s, topo8)
    slots_canon = canonical_save_state(st_s, plan).momentum
    for a, b in zip(jax.tree.leaves(slots_canon),
                    jax.tree.leaves(jax.device_get(st_r.momentum))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), **tol)


def test_lamb_all_masked_step_is_true_noop(topo8, batch64):
    """timeout_ms=0 masks every replica: params and BOTH moment slots
    come through untouched (the select guard covers multi-slot
    state)."""
    cfg = _cfg(optim={"name": "lamb"},
               parallel={"shard_weight_update": True},
               sync={"mode": "timeout", "timeout_ms": 0.0})
    model = get_model(cfg.model)
    state = topo8.device_put_state(init_train_state(model, cfg, topo8),
                                   state_partition_specs(model, cfg, topo8))
    before = jax.device_get((state.params, state.momentum))
    step_fn = build_train_step(model, cfg, topo8, constant(LR))
    state, m = step_fn(state, topo8.device_put_batch(batch64))
    assert float(m["num_contributors"]) == 0.0
    assert int(jax.device_get(state.updates_applied)) == 0
    after = jax.device_get((state.params, state.momentum))
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# gradient accumulation
# ---------------------------------------------------------------------------

def test_grad_accum_matches_large_batch(topo8, batch64):
    """accum=2 over half-size batches consumes the same sample stream
    as one double-size batch (the BatchIterator positions are
    identical), and the fp32-accumulated mean-of-means equals the
    full-batch mean — losses and params match the accum=1 run."""
    datasets = make_synthetic(num_train=1024, num_test=64)

    def trainer(accum, bs, d):
        cfg = _cfg(data={"batch_size": bs},
                   train={"max_steps": 4, "grad_accum_steps": accum,
                          "train_dir": d, "log_every_steps": 2,
                          "save_interval_steps": 0,
                          "save_results_period": 0})
        return Trainer(cfg, datasets=datasets)

    import tempfile
    with tempfile.TemporaryDirectory() as td:
        t1 = trainer(1, 128, td + "/full")
        s1 = t1.run()
        t2 = trainer(2, 64, td + "/accum")
        s2 = t2.run()
    assert t2.effective_batch == t1.effective_batch == 128
    np.testing.assert_allclose(s2["last_metrics"]["loss"],
                               s1["last_metrics"]["loss"],
                               rtol=5e-5, atol=5e-5)
    for a, b in zip(jax.tree.leaves(jax.device_get(t2.state.params)),
                    jax.tree.leaves(jax.device_get(t1.state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
    # cursor math: accum advances the SAME lockstep batch coordinate
    assert (t2.train_iter.state()["batches"] * 64
            == t1.train_iter.state()["batches"] * 128)


def test_grad_accum_quorum_masking_semantics(topo8, batch64):
    """Masks apply once per optimizer application: under quorum the
    accum step selects the same k contributors as accum=1 (step-time
    draws key off (step, replica), not microbatch)."""
    over = dict(sync={"mode": "quorum", "num_replicas_to_aggregate": 5,
                      "straggler_profile": "lognormal"},
                train={"max_steps": 3, "grad_accum_steps": 2,
                       "save_interval_steps": 0, "save_results_period": 0,
                       "log_every_steps": 3})
    cfg = _cfg(data={"batch_size": 32}, **over)
    model = get_model(cfg.model)
    state = topo8.device_put_state(init_train_state(model, cfg, topo8),
                                   state_partition_specs(model, cfg, topo8))
    step_fn = build_train_step(model, cfg, topo8, constant(LR))
    ds = make_synthetic(num_train=128, num_test=16)
    gbatch = topo8.device_put_batch({"image": ds.train.images[:64],
                                     "label": ds.train.labels[:64]})
    state, m = step_fn(state, gbatch)
    assert float(m["num_contributors"]) == 5.0
    assert np.asarray(m["flags"]).sum() == 5.0


# ---------------------------------------------------------------------------
# mixed precision: fp32 master weights over a bf16 forward
# ---------------------------------------------------------------------------

def test_master_weights_matches_f32_baseline(topo8, batch64):
    """param_dtype=bf16 + master_weights over a bf16 compute is the
    SAME compiled math as f32 params + bf16 compute (the model casts
    params to compute dtype either way); the master path must
    reproduce it and keep its state params in float32."""
    over = {"model": {"compute_dtype": "bfloat16", "dropout_rate": 0.0}}
    st_base, hist_base = _run_steps(_cfg(**over), topo8, batch64)
    st_m, hist_m = _run_steps(
        _cfg(precision={"param_dtype": "bfloat16", "master_weights": True},
             **over), topo8, batch64)
    for leaf in jax.tree.leaves(st_m.params):
        assert leaf.dtype == jnp.float32  # masters stay fp32
    for mb, mm in zip(hist_base, hist_m):
        np.testing.assert_allclose(float(mm["loss"]), float(mb["loss"]),
                                   rtol=1e-6, atol=1e-6)
    for a, b in zip(jax.tree.leaves(jax.device_get(st_m.params)),
                    jax.tree.leaves(jax.device_get(st_base.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_low_precision_without_master_stores_bf16(topo8, batch64):
    """master_weights=false + param_dtype=bf16: params live (and are
    updated) in bf16; moment slots stay float32."""
    cfg = _cfg(optim={"momentum": 0.9},
               precision={"param_dtype": "bfloat16"})
    model = get_model(cfg.model)
    state = topo8.device_put_state(init_train_state(model, cfg, topo8),
                                   state_partition_specs(model, cfg, topo8))
    for leaf in jax.tree.leaves(state.params):
        assert leaf.dtype == jnp.bfloat16
    for leaf in jax.tree.leaves(state.momentum):
        assert leaf.dtype == jnp.float32
    step_fn = build_train_step(model, cfg, topo8, constant(LR))
    state, m = step_fn(state, topo8.device_put_batch(batch64))
    for leaf in jax.tree.leaves(state.params):
        assert leaf.dtype == jnp.bfloat16
    assert np.isfinite(float(m["loss"]))


def test_master_weights_zero1_roundtrip(tmp_path, synthetic_datasets):
    """The full recipe — LAMB + master weights + ZeRO-1 — checkpoints
    masters canonically (fp32, logical shapes) and resumes bitwise;
    the artifact restores onto the replicated discipline too."""
    def cfg_for(shard, d):
        return _cfg(
            optim={"name": "lamb", "initial_learning_rate": 1e-3},
            precision={"param_dtype": "bfloat16", "master_weights": True},
            parallel={"shard_weight_update": shard},
            train={"max_steps": 4, "log_every_steps": 2,
                   "save_interval_steps": 2, "save_results_period": 0,
                   "train_dir": d, "async_checkpoint": False})

    d = str(tmp_path / "recipe")
    t1 = Trainer(cfg_for(True, d), datasets=synthetic_datasets)
    assert t1._zero1_plan is not None
    t1.run()
    digest = ckpt.state_params_digest(t1.state)
    # masters saved canonically: the artifact's params are fp32
    state_dict, _ = ckpt._checkpoint_state_dict(
        __import__("pathlib").Path(d), None)
    leaf = next(iter(jax.tree.leaves(state_dict["params"])))
    assert np.asarray(leaf).dtype == np.float32
    # LAMB slots live under the reserved {"m","v"} layout
    assert set(state_dict["momentum"]) == {"m", "v"}

    t2 = Trainer(cfg_for(True, d), datasets=synthetic_datasets)
    assert int(jax.device_get(t2.state.step)) == 4
    assert ckpt.state_params_digest(t2.state) == digest
    for a, b in zip(jax.tree.leaves(jax.device_get(t1.state.momentum)),
                    jax.tree.leaves(jax.device_get(t2.state.momentum))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    t3 = Trainer(cfg_for(False, d), datasets=synthetic_datasets)
    assert t3._zero1_plan is None
    assert ckpt.state_params_digest(t3.state) == digest


def test_lamb_digests_deterministic_and_knob_portable(tmp_path,
                                                      synthetic_datasets):
    """What the chaos determinism invariant (#3) needs from LAMB:
    same-seed same-config runs produce BITWISE-identical params AND
    opt-state digests (multi-slot state included), and the canonical
    artifact restores across the ZeRO-1 knob to a state matching
    within the trust-ratio reassociation tolerance (cross-knob
    bitwise equality is a linear-update property — see the tolerance
    note on test_trust_ratio_zero1_matches_replicated)."""
    def run(shard, d):
        t = Trainer(_cfg(
            optim={"name": "lamb", "initial_learning_rate": 1e-3},
            parallel={"shard_weight_update": shard},
            train={"max_steps": 4, "log_every_steps": 2,
                   "save_interval_steps": 2, "save_results_period": 0,
                   "train_dir": d, "async_checkpoint": False}),
            datasets=synthetic_datasets)
        t.run()
        return t

    d1, d1b = str(tmp_path / "shard"), str(tmp_path / "shard_rerun")
    d2 = str(tmp_path / "rep")
    run(True, d1)
    run(True, d1b)
    t_rep = run(False, d2)
    # determinism: same seed + same knob → bitwise-equal artifacts
    assert (ckpt.checkpoint_params_digest(d1)
            == ckpt.checkpoint_params_digest(d1b))
    assert (ckpt.checkpoint_opt_state_digest(d1)
            == ckpt.checkpoint_opt_state_digest(d1b))
    # portability: the sharded run's canonical artifact restores onto
    # the replicated discipline, states agreeing within tolerance
    cfg_rep = _cfg(
        optim={"name": "lamb", "initial_learning_rate": 1e-3},
        train={"max_steps": 4, "log_every_steps": 2,
               "save_interval_steps": 2, "save_results_period": 0,
               "train_dir": d1, "async_checkpoint": False})
    t_x = Trainer(cfg_rep, datasets=synthetic_datasets)
    assert int(jax.device_get(t_x.state.step)) == 4
    for a, b in zip(jax.tree.leaves(jax.device_get(t_x.state.params)),
                    jax.tree.leaves(jax.device_get(t_rep.state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-4)
