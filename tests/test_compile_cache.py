"""Restart-latency fast path: persistent-cache wiring, AOT cache-key
correctness (same config ⇒ hit, different config ⇒ miss), bitwise
parity of the precompiled step vs the cold-compiled one, and the disk
cache's degrade-don't-crash contract (corrupt entry, unsupported
platform)."""

import json

import jax
import pytest

from distributedmnist_tpu.core import compile_cache as cc
from distributedmnist_tpu.core.config import CompileConfig, ExperimentConfig
from distributedmnist_tpu.core.mesh import make_topology
from distributedmnist_tpu.models.registry import get_model
from distributedmnist_tpu.parallel import aot

pytestmark = pytest.mark.tier1


# ---------------------------------------------------------------------------
# config + persistent-cache wiring
# ---------------------------------------------------------------------------

def test_compile_config_roundtrip_and_unknown_key():
    cfg = ExperimentConfig.from_dict(
        {"compile": {"persistent_cache": False, "cache_dir": "/x",
                     "precompile": False}})
    assert cfg.compile.cache_dir == "/x"
    assert not cfg.compile.persistent_cache
    assert ExperimentConfig.from_dict(cfg.to_dict()).compile == cfg.compile
    from distributedmnist_tpu.core.config import ConfigError
    with pytest.raises(ConfigError, match="min_entry"):
        ExperimentConfig.from_dict({"compile": {"min_entry": 1}})


def test_resolve_cache_dir_precedence(monkeypatch, tmp_path):
    monkeypatch.delenv(cc.CACHE_DIR_ENV, raising=False)
    assert cc.resolve_cache_dir(CompileConfig()) is None
    monkeypatch.setenv(cc.CACHE_DIR_ENV, str(tmp_path / "env"))
    assert cc.resolve_cache_dir(CompileConfig()) == tmp_path / "env"
    # explicit config wins over env; the enable flag wins over both
    got = cc.resolve_cache_dir(CompileConfig(cache_dir=str(tmp_path / "c")))
    assert got == tmp_path / "c"
    assert cc.resolve_cache_dir(
        CompileConfig(persistent_cache=False,
                      cache_dir=str(tmp_path / "c"))) is None


def test_enable_persistent_cache_sets_jax_config_and_stats(tmp_path):
    d = tmp_path / "cache"
    prev = jax.config.jax_compilation_cache_dir
    try:
        # this container's jax is inside the cross-process corruption
        # quarantine — the wiring is exercised through the validated-
        # platform override (the quarantine itself is pinned below)
        got = cc.enable_persistent_cache(
            CompileConfig(cache_dir=str(d), trust_cache_cross_process=True))
        assert got == d and d.is_dir()
        assert jax.config.jax_compilation_cache_dir == str(d)
        import jax.numpy as jnp
        # a program no earlier test can have compiled: jax's in-memory
        # compilation LRU sits ABOVE the persistent cache, and an
        # aliased HLO would never reach the disk layer this test is
        # about (hash() is process-salted, so the constant is unique
        # per run and the HLO unique in this process)
        k = float(hash(str(d)) % 9973 + 2)
        jax.jit(lambda x: (x * k).sum())(jnp.ones((4,))).block_until_ready()
        stats = cc.cache_stats(d)
        assert stats["entries"] >= 1 and stats["bytes"] > 0
        # the monitoring listener fed the counters (this jax has them)
        assert stats["hits"] + stats["misses"] >= 1
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
        # drop the now-stale cache object too: it holds the tmp dir
        # pytest is about to delete, and later multi-threaded compiles
        # against a stale cache have been observed to corrupt the
        # process on jax 0.4.37
        from jax._src import compilation_cache as _ccache
        _ccache.reset_cache()
        cc._enabled_dir = None


@pytest.mark.skipif(cc.cross_process_reuse_quarantined() is None,
                    reason="this jax is outside the corruption quarantine")
def test_cache_quarantine_on_known_bad_jax(tmp_path):
    """jax <= 0.4.37 deserializes corrupt executables cross-process
    (wrong numerics then SIGSEGV on restarted workers — measured 13/13
    on this container): by DEFAULT both cache layers refuse, and only
    the explicit validated-platform override re-enables them."""
    d = tmp_path / "q"
    prev = jax.config.jax_compilation_cache_dir
    try:
        assert cc.enable_persistent_cache(
            CompileConfig(cache_dir=str(d))) is None
        assert jax.config.jax_compilation_cache_dir == prev
        assert not d.exists()  # refused before any side effect
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
    # the AOT disk cache refuses BOTH directions untrusted...
    fn, args = _jit_and_args()
    _, info = aot.aot_compile(fn, args, cache_dir=tmp_path, key="kq")
    assert info["source"] == "compiled" and info["serialized"] is False
    assert not (tmp_path / "aot" / "kq.exe").exists()
    # ...and a pre-existing foreign entry is never loaded untrusted
    _, info_t = aot.aot_compile(fn, args, cache_dir=tmp_path, key="kq",
                                trust_cross_process=True)
    if info_t["serialized"]:  # platform can serialize: plant foreign pid
        import os
        import pickle
        entry = tmp_path / "aot" / "kq.exe"
        pid, *rest = pickle.loads(entry.read_bytes())
        entry.write_bytes(pickle.dumps((pid + 1, *rest)))
        fn2, _ = _jit_and_args()
        _, info2 = aot.aot_compile(fn2, args, cache_dir=tmp_path, key="kq")
        assert info2["source"] == "compiled"  # quarantined: not aot_disk
    # config surface: the override round-trips
    cfg = ExperimentConfig.from_dict(
        {"compile": {"trust_cache_cross_process": True}})
    assert cfg.compile.trust_cache_cross_process is True


# ---------------------------------------------------------------------------
# AOT cache key: hit on identity, miss on any topology/config change
# ---------------------------------------------------------------------------

def test_aot_cache_key_same_triple_hits_different_misses(topo8):
    cfg = ExperimentConfig.from_dict({"model": {"compute_dtype": "float32"}})
    model = get_model(cfg.model)
    k1 = aot.aot_cache_key(model, cfg, topo8)
    k2 = aot.aot_cache_key(get_model(cfg.model), ExperimentConfig.from_dict(
        {"model": {"compute_dtype": "float32"}}), topo8)
    assert k1 == k2  # same (model, cfg, topo) ⇒ same key
    # any config change ⇒ different executable ⇒ different key
    assert aot.aot_cache_key(
        model, cfg.override({"data.batch_size": 64}), topo8) != k1
    assert aot.aot_cache_key(
        model, cfg.override({"sync.mode": "quorum"}), topo8) != k1
    # a different topology must never reuse a stale executable
    from distributedmnist_tpu.core.config import MeshConfig
    topo_tp = make_topology(MeshConfig(num_replicas=4, model_parallelism=2))
    assert aot.aot_cache_key(model, cfg, topo_tp) != k1
    assert aot.aot_cache_key(model, cfg, topo8, what="eval") != k1
    # host-side knobs (run length, cadence, dirs) never enter the
    # lowered program — bumping them must HIT, not recompile cold
    assert aot.aot_cache_key(
        model, cfg.override({"train.max_steps": 999}), topo8) == k1
    assert aot.aot_cache_key(
        model, cfg.override({"train.log_every_steps": 7}), topo8) == k1


# ---------------------------------------------------------------------------
# precompiled step ≡ cold-compiled step, bitwise
# ---------------------------------------------------------------------------

def _tiny_cfg(train_dir: str, precompile: bool) -> ExperimentConfig:
    return ExperimentConfig.from_dict({
        "data": {"dataset": "synthetic", "batch_size": 32,
                 "synthetic_train_size": 256, "synthetic_test_size": 64},
        "model": {"compute_dtype": "float32"},
        # 2 replicas, not the full 8: the test pays TWO train-step
        # compiles (precompiled + cold arms) and the bitwise claim is
        # mesh-size-independent — keep the tier-1 budget
        "mesh": {"num_replicas": 2},
        "compile": {"precompile": precompile},
        "train": {"max_steps": 2, "train_dir": train_dir,
                  "log_every_steps": 1, "save_interval_steps": 0,
                  "save_results_period": 0, "async_checkpoint": False,
                  "summary_every_steps": 0}})


def test_precompile_first_step_bitwise_equals_cold(tmp_path):
    from distributedmnist_tpu.train.loop import Trainer
    t_pre = Trainer(_tiny_cfg(str(tmp_path / "pre"), precompile=True))
    info = t_pre.precompile()
    assert info["compile_s"] is not None and info["source"] == "compiled"
    assert t_pre.precompile() is info  # idempotent per Trainer
    s_pre = t_pre.run()
    t_cold = Trainer(_tiny_cfg(str(tmp_path / "cold"), precompile=False))
    s_cold = t_cold.run()
    # the AOT executable and jit's own compile are the same program:
    # losses and final params must match BITWISE, not approximately
    pre = [json.loads(l) for l in
           (tmp_path / "pre" / "train_log.jsonl").read_text().splitlines()]
    cold = [json.loads(l) for l in
            (tmp_path / "cold" / "train_log.jsonl").read_text().splitlines()]
    assert [r["loss"] for r in pre if r["event"] == "step"] == \
           [r["loss"] for r in cold if r["event"] == "step"]
    assert s_pre["params_digest"] == s_cold["params_digest"]
    # compile time is journaled separately from step time
    compile_events = [r for r in pre if r["event"] == "compile"]
    assert len(compile_events) == 1
    assert compile_events[0]["compile_s"] == info["compile_s"]
    assert s_pre["compile"]["source"] == "compiled"
    assert s_cold["compile"] is None


# ---------------------------------------------------------------------------
# executable disk cache: roundtrip, corruption, unsupported platform
# ---------------------------------------------------------------------------

def _jit_and_args():
    import jax.numpy as jnp
    fn = jax.jit(lambda x: (x * 3.0).sum())
    return fn, (jnp.arange(8, dtype=jnp.float32),)


def test_aot_disk_cache_roundtrip_and_corruption(tmp_path):
    # trust override: the roundtrip mechanics under test are what the
    # quarantine (tested above) would otherwise short-circuit
    def compile_trusted(fn, args, **kw):
        return aot.aot_compile(fn, args, trust_cross_process=True, **kw)

    fn, args = _jit_and_args()
    compiled, info = compile_trusted(fn, args, cache_dir=tmp_path, key="k1")
    assert info["source"] == "compiled"
    assert float(compiled(*args)) == float(fn(*args))
    if not info["serialized"]:
        pytest.skip("platform cannot serialize executables — the "
                    "unsupported-marker path is covered below")
    # an entry THIS process stored is refused (measured 0.4.37 hazard:
    # same-process deserialize of a real train step corrupts the
    # runtime) — the load quietly falls back to a compile
    fn2, _ = _jit_and_args()
    _, info_same = compile_trusted(fn2, args, cache_dir=tmp_path, key="k1")
    assert info_same["source"] == "compiled"
    # a FOREIGN process's entry (different stored pid) is served from
    # disk with a bitwise-identical result — the restart fast path
    import os
    import pickle
    entry = tmp_path / "aot" / "k1.exe"
    pid, *rest = pickle.loads(entry.read_bytes())
    assert pid == os.getpid()
    entry.write_bytes(pickle.dumps((pid + 1, *rest)))
    compiled2, info2 = compile_trusted(fn2, args, cache_dir=tmp_path,
                                       key="k1")
    assert info2["source"] == "aot_disk"
    assert float(compiled2(*args)) == float(compiled(*args))
    # a DIFFERENT key is a miss, never a stale reuse
    _, info3 = compile_trusted(fn2, args, cache_dir=tmp_path, key="k-other")
    assert info3["source"] == "compiled"
    # corrupt the entry: logged fallback to cold compile, entry healed
    # (deleted), never a crash
    entry = tmp_path / "aot" / "k1.exe"
    entry.write_bytes(b"torn garbage, not a pickle")
    import logging
    msgs: list[str] = []
    handler = logging.Handler()
    handler.emit = lambda rec: msgs.append(rec.getMessage())
    logging.getLogger("distributedmnist_tpu.aot").addHandler(handler)
    try:
        compiled4, info4 = compile_trusted(fn2, args, cache_dir=tmp_path,
                                           key="k1")
    finally:
        logging.getLogger("distributedmnist_tpu.aot").removeHandler(handler)
    assert info4["source"] == "compiled"
    assert float(compiled4(*args)) == float(compiled(*args))
    # the fallback is LOGGED and the torn entry healed (deleted, then
    # re-serialized by the recompile) — never a crash
    assert any("corrupt AOT cache entry" in m for m in msgs)
    assert not entry.exists() or info4["serialized"]


def test_aot_unsupported_platform_marker_short_circuits(tmp_path):
    """A backend deserialize failure (the cross-process CPU case) marks
    the cache dir unsupported; later processes skip the probe and go
    straight to the compile (persistent-cache-warm) path."""
    fn, args = _jit_and_args()
    cache = aot.ExecutableCache(tmp_path, trust_cross_process=True)
    assert not cache.serialization_known_unsupported()
    cache._mark_unsupported(RuntimeError("Symbols not found"))
    assert cache.serialization_known_unsupported()
    # load AND store now short-circuit without touching the backend
    assert cache.load("k1") is None
    compiled, info = aot.aot_compile(fn, args, cache_dir=tmp_path, key="k1",
                                     trust_cross_process=True)
    assert info["source"] == "compiled" and info["serialized"] is False
    assert not (tmp_path / "aot" / "k1.exe").exists()
    assert float(compiled(*args)) == float(fn(*args))
    # the verdict is about ONE (platform, device_kind, jax) triple: a
    # marker left behind by a different runtime (jaxlib upgrade, cache
    # dir moved across backends) must re-probe, not disable forever
    marker = tmp_path / "aot" / "SERIALIZATION_UNSUPPORTED"
    rec = json.loads(marker.read_text())
    rec["runtime"]["jax"] = "0.0.0"
    marker.write_text(json.dumps(rec))
    assert not cache.serialization_known_unsupported()
    # a legacy/torn (non-JSON) marker also reads as "probe again"
    marker.write_text("RuntimeError: Symbols not found\n")
    assert not cache.serialization_known_unsupported()
