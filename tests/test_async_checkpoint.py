"""Async checkpoint writer: durability parity with the sync path,
latest-wins coalescing, error surfacing, and the Trainer integration
(final save drains before run() returns)."""

import numpy as np
import pytest

from conftest import base_config
from distributedmnist_tpu.train import checkpoint as ckpt


def _state():
    return {"w": np.arange(8.0), "b": np.float32(3.0)}


def test_async_matches_sync_roundtrip(tmp_path):
    sync_dir, async_dir = tmp_path / "sync", tmp_path / "async"
    ckpt.save_checkpoint(sync_dir, _state(), 7, extra={"k": 1})

    ac = ckpt.AsyncCheckpointer()
    ac.save(async_dir, _state(), 7, extra={"k": 1})
    ac.close()

    a = ckpt.restore_checkpoint(sync_dir, _state())
    b = ckpt.restore_checkpoint(async_dir, _state())
    assert a is not None and b is not None
    np.testing.assert_array_equal(a[0]["w"], b[0]["w"])
    assert a[1] == b[1] == {"k": 1}
    assert a[2] == b[2] == 7


def test_latest_wins_and_final_step_durable(tmp_path):
    ac = ckpt.AsyncCheckpointer()
    for step in range(1, 30):
        ac.save(tmp_path, {"w": np.full(4, float(step))}, step, keep=50)
    ac.wait()
    # intermediate steps may coalesce, but the LAST must be on disk
    assert ckpt.latest_checkpoint_step(tmp_path) == 29
    restored = ckpt.restore_checkpoint(tmp_path, {"w": np.zeros(4)})
    np.testing.assert_array_equal(restored[0]["w"], np.full(4, 29.0))
    ac.close()


def test_worker_error_surfaces(tmp_path):
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("file, not dir")  # mkdir inside save will fail
    ac = ckpt.AsyncCheckpointer()
    ac.save(blocker, _state(), 1)
    with pytest.raises(RuntimeError, match="async checkpoint"):
        ac.wait()
    # the error is consumed; the writer keeps working afterwards
    ac.save(tmp_path, _state(), 2)
    ac.close()
    assert ckpt.latest_checkpoint_step(tmp_path) == 2


def test_trainer_async_checkpoint_resume(tmp_train_dir):
    from distributedmnist_tpu.train.loop import Trainer

    cfg = base_config(
        train={"max_steps": 6, "train_dir": tmp_train_dir,
               "save_interval_secs": 0, "save_interval_steps": 3,
               "async_checkpoint": True})
    tr = Trainer(cfg)
    assert tr._use_async_ckpt
    tr.run()
    assert tr._checkpointer is None  # writer thread joined at run() end
    assert ckpt.latest_checkpoint_step(tmp_train_dir) == 6

    tr2 = Trainer(cfg.override({"train.max_steps": 8}))
    assert tr2._start_step == 6
    assert tr2.run()["final_step"] == 8


def test_prepare_runs_on_worker_thread_and_fails_like_a_write(tmp_path):
    """The donation-safe snapshot seam: ``prepare`` (D2H + canonical
    conversion) executes on the WORKER thread — never the caller's —
    and a prepare failure surfaces exactly like a failed write."""
    import threading

    caller = threading.current_thread().name
    seen: list[str] = []

    def prepare(state):
        seen.append(threading.current_thread().name)
        return {"w": state["w"] * 2}

    ac = ckpt.AsyncCheckpointer()
    ac.save(tmp_path, {"w": np.arange(4.0)}, 3, prepare=prepare)
    ac.wait()
    assert seen and seen[0] != caller  # ran on ckpt-writer, not here
    got = ckpt.restore_checkpoint(tmp_path, {"w": np.zeros(4)})
    np.testing.assert_array_equal(got[0]["w"], np.arange(4.0) * 2)

    def bad_prepare(state):
        raise ValueError("snapshot conversion exploded")

    ac.save(tmp_path, {"w": np.arange(4.0)}, 4, prepare=bad_prepare)
    with pytest.raises(RuntimeError, match="async checkpoint"):
        ac.wait()
    ac.close()
    assert ckpt.latest_checkpoint_step(tmp_path) == 3  # step 4 never landed


def test_trainer_async_snapshot_journals_save_stall(tmp_train_dir):
    """train.async_snapshot (the default): every save lands a
    journaled ``event: "save"`` with save_stall_ms, the timing report
    carries the snapshot_stall_ms stats, and the artifact roundtrips
    bitwise against a sync-fetch (async_snapshot=false) run."""
    import json
    from pathlib import Path

    from distributedmnist_tpu.train.loop import Trainer

    def run(d, async_snapshot):
        cfg = base_config(
            optim={"momentum": 0.9},
            parallel={"shard_weight_update": True},
            train={"max_steps": 4, "train_dir": d, "log_every_steps": 2,
                   "save_interval_secs": 0, "save_interval_steps": 2,
                   "save_results_period": 0, "async_checkpoint": True,
                   "async_snapshot": async_snapshot})
        t = Trainer(cfg)
        assert t._async_snapshot is async_snapshot
        return t.run()

    d_async = tmp_train_dir + "_a"
    d_sync = tmp_train_dir + "_s"
    sa = run(d_async, True)
    ss = run(d_sync, False)
    # identical artifacts either way — the snapshot path is a latency
    # change, not a numerics one
    assert (ckpt.checkpoint_params_digest(d_async)
            == ckpt.checkpoint_params_digest(d_sync))
    assert (ckpt.checkpoint_opt_state_digest(d_async)
            == ckpt.checkpoint_opt_state_digest(d_sync))
    for d, flag, summary in ((d_async, True, sa), (d_sync, False, ss)):
        recs = [json.loads(l) for l in
                (Path(d) / "train_log.jsonl").read_text().splitlines()]
        saves = [r for r in recs if r.get("event") == "save"]
        assert saves, "no save events journaled"
        assert all(r["async_snapshot"] is flag and r["save_stall_ms"] >= 0
                   for r in saves)
        assert summary["timing"]["snapshot_stall_ms"]["count"] == len(saves)


def test_save_escalates_after_consecutive_failures(tmp_path):
    # A file where the checkpoint *directory* should be makes every
    # write fail the same way a persistently broken disk would.
    blocker = tmp_path / "blocked"
    blocker.write_text("not a dir")
    ac = ckpt.AsyncCheckpointer(max_consecutive_failures=3)
    for step in range(1, 4):
        ac.save(blocker, _state(), step)
        with pytest.raises(RuntimeError):
            ac.wait()  # each failed write surfaces on drain
    # the 4th save refuses up-front: checkpoints are persistently stale
    with pytest.raises(RuntimeError, match="consecutive"):
        ac.save(blocker, _state(), 4)
