"""Async checkpoint writer: durability parity with the sync path,
latest-wins coalescing, error surfacing, and the Trainer integration
(final save drains before run() returns)."""

import numpy as np
import pytest

from conftest import base_config
from distributedmnist_tpu.train import checkpoint as ckpt


def _state():
    return {"w": np.arange(8.0), "b": np.float32(3.0)}


def test_async_matches_sync_roundtrip(tmp_path):
    sync_dir, async_dir = tmp_path / "sync", tmp_path / "async"
    ckpt.save_checkpoint(sync_dir, _state(), 7, extra={"k": 1})

    ac = ckpt.AsyncCheckpointer()
    ac.save(async_dir, _state(), 7, extra={"k": 1})
    ac.close()

    a = ckpt.restore_checkpoint(sync_dir, _state())
    b = ckpt.restore_checkpoint(async_dir, _state())
    assert a is not None and b is not None
    np.testing.assert_array_equal(a[0]["w"], b[0]["w"])
    assert a[1] == b[1] == {"k": 1}
    assert a[2] == b[2] == 7


def test_latest_wins_and_final_step_durable(tmp_path):
    ac = ckpt.AsyncCheckpointer()
    for step in range(1, 30):
        ac.save(tmp_path, {"w": np.full(4, float(step))}, step, keep=50)
    ac.wait()
    # intermediate steps may coalesce, but the LAST must be on disk
    assert ckpt.latest_checkpoint_step(tmp_path) == 29
    restored = ckpt.restore_checkpoint(tmp_path, {"w": np.zeros(4)})
    np.testing.assert_array_equal(restored[0]["w"], np.full(4, 29.0))
    ac.close()


def test_worker_error_surfaces(tmp_path):
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("file, not dir")  # mkdir inside save will fail
    ac = ckpt.AsyncCheckpointer()
    ac.save(blocker, _state(), 1)
    with pytest.raises(RuntimeError, match="async checkpoint"):
        ac.wait()
    # the error is consumed; the writer keeps working afterwards
    ac.save(tmp_path, _state(), 2)
    ac.close()
    assert ckpt.latest_checkpoint_step(tmp_path) == 2


def test_trainer_async_checkpoint_resume(tmp_train_dir):
    from distributedmnist_tpu.train.loop import Trainer

    cfg = base_config(
        train={"max_steps": 6, "train_dir": tmp_train_dir,
               "save_interval_secs": 0, "save_interval_steps": 3,
               "async_checkpoint": True})
    tr = Trainer(cfg)
    assert tr._use_async_ckpt
    tr.run()
    assert tr._checkpointer is None  # writer thread joined at run() end
    assert ckpt.latest_checkpoint_step(tmp_train_dir) == 6

    tr2 = Trainer(cfg.override({"train.max_steps": 8}))
    assert tr2._start_step == 6
    assert tr2.run()["final_step"] == 8


def test_save_escalates_after_consecutive_failures(tmp_path):
    # A file where the checkpoint *directory* should be makes every
    # write fail the same way a persistently broken disk would.
    blocker = tmp_path / "blocked"
    blocker.write_text("not a dir")
    ac = ckpt.AsyncCheckpointer(max_consecutive_failures=3)
    for step in range(1, 4):
        ac.save(blocker, _state(), step)
        with pytest.raises(RuntimeError):
            ac.wait()  # each failed write surfaces on drain
    # the 4th save refuses up-front: checkpoints are persistently stale
    with pytest.raises(RuntimeError, match="consecutive"):
        ac.save(blocker, _state(), 4)
