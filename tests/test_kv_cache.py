"""Paged KV cache: allocator property tests (no double-assignment,
pool conservation, all-or-nothing alloc) and the block-table-reads ==
dense-reference-cache oracle the decode step's correctness rests on."""

import numpy as np
import pytest


@pytest.mark.tier1
def test_allocator_alloc_free_interleavings_property():
    """Seeded random alloc/free interleavings: live allocations are
    always disjoint, the free list conserves the pool exactly, the
    null block is never handed out, and a failed alloc takes nothing."""
    from distributedmnist_tpu.servesvc.kv_cache import (NULL_BLOCK,
                                                       BlockAllocator)

    rng = np.random.default_rng(0)
    for trial in range(20):
        num_blocks = int(rng.integers(2, 40))
        alloc = BlockAllocator(num_blocks)
        live: list[tuple[int, ...]] = []
        for _ in range(200):
            if live and rng.random() < 0.45:
                got = live.pop(int(rng.integers(len(live))))
                alloc.free(got)
            else:
                n = int(rng.integers(0, num_blocks))
                got = alloc.alloc(n)
                if got is None:
                    # all-or-nothing: a refused alloc changed nothing
                    assert n > alloc.available
                    continue
                assert len(got) == n
                live.append(got)
            flat = [b for blocks in live for b in blocks]
            # never double-assigned, never the null block
            assert len(flat) == len(set(flat))
            assert NULL_BLOCK not in flat
            # conservation: free + live == the allocatable pool
            assert alloc.available + len(flat) == num_blocks - 1
            assert alloc.in_use == set(flat)
        for blocks in live:
            alloc.free(blocks)
        assert alloc.available == num_blocks - 1


@pytest.mark.tier1
def test_allocator_double_free_refused():
    from distributedmnist_tpu.servesvc.kv_cache import BlockAllocator

    alloc = BlockAllocator(8)
    got = alloc.alloc(3)
    alloc.free(got)
    with pytest.raises(ValueError, match="double free"):
        alloc.free(got[:1])


@pytest.mark.tier1
def test_block_table_reads_equal_dense_reference():
    """Write three sequences of wildly different lengths through the
    paged scatter, read each back through its block table — bytes must
    equal a dense per-sequence reference cache."""
    import jax.numpy as jnp

    from distributedmnist_tpu.servesvc.kv_cache import PagedKVCache

    L, H, HD, BS = 2, 3, 4, 4
    cache = PagedKVCache(L, 32, BS, H, HD, max_blocks_per_seq=8,
                         dtype=jnp.float32)
    rng = np.random.default_rng(1)
    seqs = []
    for length in (3, 9, 17):  # straddles 1, 3 and 5 blocks
        table = cache.alloc_sequence(length)
        assert table is not None and table.shape == (8,)
        s_pad = 32  # deliberately over-padded: padding must not leak
        ks = rng.normal(size=(L, s_pad, H, HD)).astype(np.float32)
        vs = rng.normal(size=(L, s_pad, H, HD)).astype(np.float32)
        cache.write_prompt(table, ks, vs, length)
        seqs.append((table, length, ks, vs))
    for table, length, ks, vs in seqs:
        got_k, got_v = cache.gather_dense(table, length)
        np.testing.assert_array_equal(got_k, ks[:, :length])
        np.testing.assert_array_equal(got_v, vs[:, :length])
    # freeing one sequence leaves the others' bytes untouched
    table0, *_ = seqs[0]
    cache.free_sequence(table0)
    for table, length, ks, vs in seqs[1:]:
        got_k, _ = cache.gather_dense(table, length)
        np.testing.assert_array_equal(got_k, ks[:, :length])


@pytest.mark.tier1
def test_alloc_sequence_block_pressure_and_free_cycle():
    import jax.numpy as jnp

    from distributedmnist_tpu.servesvc.kv_cache import PagedKVCache

    cache = PagedKVCache(1, 8, 4, 1, 2, max_blocks_per_seq=4,
                         dtype=jnp.float32)
    t1 = cache.alloc_sequence(16)  # 4 blocks
    t2 = cache.alloc_sequence(12)  # 3 blocks → pool exhausted (7 total)
    assert t1 is not None and t2 is not None
    assert cache.alloc_sequence(4) is None  # pressure: defer, not crash
    cache.free_sequence(t1)
    assert cache.alloc_sequence(4) is not None
    with pytest.raises(ValueError, match="max_blocks_per_seq"):
        cache.alloc_sequence(100)
