"""Sweep runner + CLI tests (≙ tools/benchmark.py / tools/tf_ec2.py roles)."""

import json

import pytest

from conftest import base_config


def test_run_experiment_produces_record(tmp_path, synthetic_datasets):
    from distributedmnist_tpu.launch.sweep import run_experiment
    cfg = base_config(name="exp_sync",
                      sync={"mode": "quorum", "num_replicas_to_aggregate": 4,
                            "straggler_profile": "lognormal"},
                      train={"max_steps": 15, "log_every_steps": 5})
    rec = run_experiment(cfg, tmp_path, datasets=synthetic_datasets)
    assert rec["name"] == "exp_sync"
    assert rec["steps"] == 15
    assert 0.0 <= rec["test_accuracy"] <= 1.0
    assert (tmp_path / "exp_sync" / "result.json").exists()
    assert (tmp_path / "exp_sync" / "config.json").exists()


def test_run_sweep_report(tmp_path, synthetic_datasets):
    from distributedmnist_tpu.launch.sweep import run_sweep
    cfgs = [base_config(name=f"s{k}",
                        sync={"mode": "quorum", "num_replicas_to_aggregate": k,
                              "straggler_profile": "lognormal"},
                        train={"max_steps": 8, "log_every_steps": 4})
            for k in (2, 8)]
    records = run_sweep(cfgs, tmp_path, datasets=synthetic_datasets)
    assert len(records) == 2
    report = (tmp_path / "report.md").read_text()
    assert "s2" in report and "s8" in report
    lines = (tmp_path / "sweep_results.jsonl").read_text().strip().split("\n")
    assert len(lines) == 2
    assert (tmp_path / "step_time_cdf.png").exists()


def test_campaign_finalize_regenerates_reports(tmp_path, synthetic_datasets):
    """run_campaign.finalize rebuilds every group report + the summary
    from sweep_results.jsonl on disk, prunes checkpoint payloads, and
    is idempotent — the recovery path when analysis code improves after
    a multi-hour campaign already ran."""
    import run_campaign
    from distributedmnist_tpu.launch.sweep import run_sweep

    gdir = tmp_path / "groupA"
    cfgs = [base_config(name=f"s{k}",
                        sync={"mode": "quorum", "num_replicas_to_aggregate": k,
                              "straggler_profile": "lognormal"},
                        train={"max_steps": 8, "log_every_steps": 4})
            for k in (2, 8)]
    run_sweep(cfgs, gdir, datasets=synthetic_datasets)
    (gdir / "report.md").unlink()  # simulate stale/missing analysis
    assert list(gdir.rglob("ckpt-*.msgpack"))

    run_campaign.finalize(tmp_path)
    report = (gdir / "report.md").read_text()
    assert "modeled" in report and "s2" in report
    summary = json.loads((tmp_path / "campaign_summary.json").read_text())
    assert [r["name"] for r in summary["groups"]["groupA"]] == ["s2", "s8"]
    assert not list(gdir.rglob("ckpt-*.msgpack"))  # pruned
    run_campaign.finalize(tmp_path)  # idempotent
    assert (gdir / "report.md").exists()


def test_load_sweep_configs_rejects_duplicates(tmp_path):
    from distributedmnist_tpu.launch.sweep import load_sweep_configs
    (tmp_path / "a.json").write_text(json.dumps({"name": "dup"}))
    (tmp_path / "b.json").write_text(json.dumps({"name": "dup"}))
    with pytest.raises(ValueError):
        load_sweep_configs(tmp_path)


def test_repo_sweep_configs_all_parse():
    """Every shipped config must load cleanly — the grid in configs/
    AND every config in subdirectories (configs/repro/…), so a broken
    repro config can't hide from CI behind the non-recursive sweep
    loader."""
    from pathlib import Path
    from distributedmnist_tpu.launch.sweep import load_sweep_configs
    root = Path(__file__).resolve().parent.parent / "configs"
    cfgs = load_sweep_configs(root)
    assert len(cfgs) >= 15
    modes = {c.sync.mode for c in cfgs}
    assert {"quorum", "interval", "cdf", "sync", "timeout"} <= modes
    subdir_cfgs = [load_sweep_configs(f)[0]
                   for sub in sorted(p for p in root.iterdir() if p.is_dir())
                   for f in sorted(sub.glob("*.json"))]
    names = {c.name for c in subdir_cfgs}
    assert "mnist_99" in names  # the one-command 99% repro config


def test_sweep_restores_ambient_mesh(tmp_path):
    """A sweep mixing a simulated-mesh config with ambient-mesh ones
    must run each on ITS mesh: the 4-device config forces 4 virtual
    devices, and the following plain config gets the ambient 8 back
    (ensure_mesh). Without the restore, every config after a
    quorum50-style entry silently runs (and records) wide experiments
    under its narrow name. Subprocess: clear_backends would invalidate
    this session's device handles."""
    import subprocess
    import sys
    script = f"""
import json
from distributedmnist_tpu.core.mesh import simulate_devices
simulate_devices(8)  # the ambient mesh (what conftest does)
from distributedmnist_tpu.core.config import ExperimentConfig
from distributedmnist_tpu.launch.sweep import run_sweep
base = {{"data": {{"dataset": "synthetic", "batch_size": 64,
                   "synthetic_train_size": 256, "synthetic_test_size": 128,
                   "use_native_pipeline": False}},
         "model": {{"compute_dtype": "float32"}},
         "train": {{"max_steps": 2, "log_every_steps": 1,
                    "save_interval_steps": 0, "save_results_period": 0}}}}
cfgs = [ExperimentConfig.from_dict(dict(base, name="sim4",
                                        mesh={{"simulate_devices": 4}})),
        ExperimentConfig.from_dict(dict(base, name="ambient"))]
recs = run_sweep(cfgs, r"{tmp_path}")
print(json.dumps([[r["name"], r["num_replicas"]] for r in recs]))
"""
    out = subprocess.run([sys.executable, "-c", script], capture_output=True,
                         text=True, timeout=420)
    assert out.returncode == 0, out.stderr[-2000:]
    got = json.loads(out.stdout.strip().splitlines()[-1])
    assert got == [["sim4", 4], ["ambient", 8]], got


def test_ensure_mesh_noop_and_nonrestorable():
    """ensure_mesh: matching device set → no backend teardown (device
    objects stay valid); ambient-non-CPU + mismatch → loud error, never
    a silent wrong-mesh run."""
    import jax
    from distributedmnist_tpu.core import mesh as mesh_mod

    devs_before = jax.devices()
    mesh_mod.ensure_mesh(8)   # conftest mesh is already 8 CPU devices
    mesh_mod.ensure_mesh(0)   # ambient == current → noop
    assert jax.devices() == devs_before  # no clear_backends happened

    saved = mesh_mod._ambient_mesh
    try:
        # simulate a process whose ambient backend was a real TPU: a
        # restore to ambient cannot re-force an accelerator
        mesh_mod._ambient_mesh = (1, "tpu")
        with pytest.raises(RuntimeError, match="own process"):
            mesh_mod.ensure_mesh(0)
    finally:
        mesh_mod._ambient_mesh = saved


def test_campaign_groups_resolve_to_configs():
    """Every name the campaign driver would run must resolve to a
    loadable config — including repro_mnist99, whose config lives in
    configs/repro/ (the same fallback run_group applies)."""
    from pathlib import Path
    from distributedmnist_tpu.core.config import ExperimentConfig
    from distributedmnist_tpu.launch.campaign import (EVALUATED_RUNS, GROUPS,
                                                      resolve_config_path)
    root = Path(__file__).resolve().parent.parent / "configs"
    all_names = set()
    for names in GROUPS.values():
        for name in names:
            cfg = ExperimentConfig.from_file(resolve_config_path(root, name))
            assert cfg.name == name
            all_names.add(name)
    assert "mnist_99" in all_names
    assert EVALUATED_RUNS <= all_names  # evaluator targets are real runs


def test_cli_devices(capsys):
    from distributedmnist_tpu.launch.__main__ import main
    main(["devices"])
    out = json.loads(capsys.readouterr().out)
    assert out["process_count"] == 1
    assert len(out["devices"]) == 8


def test_cli_train_with_overrides(tmp_path, capsys):
    from distributedmnist_tpu.launch.__main__ import main
    main(["train",
          "data.dataset=synthetic", "data.batch_size=64",
          "data.synthetic_train_size=512", "data.synthetic_test_size=128",
          "model.compute_dtype=float32",
          "train.max_steps=6", "train.log_every_steps=3",
          f"train.train_dir={tmp_path}/t", "train.save_interval_steps=0"])
    out = json.loads(capsys.readouterr().out.strip().split("\n")[-1])
    assert out["summary"]["final_step"] == 6
    assert "accuracy" in out["test"]
