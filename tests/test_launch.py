"""Sweep runner + CLI tests (≙ tools/benchmark.py / tools/tf_ec2.py roles)."""

import json

import pytest

from conftest import base_config


def test_run_experiment_produces_record(tmp_path, synthetic_datasets):
    from distributedmnist_tpu.launch.sweep import run_experiment
    cfg = base_config(name="exp_sync",
                      sync={"mode": "quorum", "num_replicas_to_aggregate": 4,
                            "straggler_profile": "lognormal"},
                      train={"max_steps": 15, "log_every_steps": 5})
    rec = run_experiment(cfg, tmp_path, datasets=synthetic_datasets)
    assert rec["name"] == "exp_sync"
    assert rec["steps"] == 15
    assert 0.0 <= rec["test_accuracy"] <= 1.0
    assert (tmp_path / "exp_sync" / "result.json").exists()
    assert (tmp_path / "exp_sync" / "config.json").exists()


def test_run_experiment_is_fresh_not_resumed(tmp_path, synthetic_datasets):
    """A re-run into an existing results dir must train from step 0,
    not silently resume from the previous attempt's checkpoint: a
    resume reports steps=final_step while the timing arrays cover only
    the post-resume tail (two interval-sweep rows shipped that way).
    ``steps == timing.num_steps`` is the consistency invariant."""
    from distributedmnist_tpu.launch.sweep import run_experiment
    cfg = base_config(name="fresh_check",
                      train={"max_steps": 6, "log_every_steps": 3,
                             "save_interval_steps": 3})
    first = run_experiment(cfg, tmp_path, datasets=synthetic_datasets)
    assert first["steps"] == first["timing"]["num_steps"] == 6
    # second run with a RAISED budget over the same dir (the leftover
    # step-6 checkpoint is the trap)
    cfg2 = base_config(name="fresh_check",
                       train={"max_steps": 10, "log_every_steps": 5,
                              "save_interval_steps": 5})
    rec = run_experiment(cfg2, tmp_path, datasets=synthetic_datasets)
    assert rec["steps"] == rec["timing"]["num_steps"] == 10


def test_run_sweep_report(tmp_path, synthetic_datasets):
    from distributedmnist_tpu.launch.sweep import run_sweep
    cfgs = [base_config(name=f"s{k}",
                        sync={"mode": "quorum", "num_replicas_to_aggregate": k,
                              "straggler_profile": "lognormal"},
                        train={"max_steps": 8, "log_every_steps": 4})
            for k in (2, 8)]
    records = run_sweep(cfgs, tmp_path, datasets=synthetic_datasets)
    assert len(records) == 2
    report = (tmp_path / "report.md").read_text()
    assert "s2" in report and "s8" in report
    lines = (tmp_path / "sweep_results.jsonl").read_text().strip().split("\n")
    assert len(lines) == 2
    assert (tmp_path / "step_time_cdf.png").exists()


def test_campaign_finalize_regenerates_reports(tmp_path, synthetic_datasets):
    """run_campaign.finalize rebuilds every group report + the summary
    from sweep_results.jsonl on disk, prunes checkpoint payloads, and
    is idempotent — the recovery path when analysis code improves after
    a multi-hour campaign already ran."""
    import run_campaign
    from distributedmnist_tpu.launch.sweep import run_sweep

    gdir = tmp_path / "groupA"
    cfgs = [base_config(name=f"s{k}",
                        sync={"mode": "quorum", "num_replicas_to_aggregate": k,
                              "straggler_profile": "lognormal"},
                        train={"max_steps": 8, "log_every_steps": 4})
            for k in (2, 8)]
    run_sweep(cfgs, gdir, datasets=synthetic_datasets)
    (gdir / "report.md").unlink()  # simulate stale/missing analysis
    assert list(gdir.rglob("ckpt-*.msgpack"))

    run_campaign.finalize(tmp_path)
    report = (gdir / "report.md").read_text()
    assert "modeled" in report and "s2" in report
    summary = json.loads((tmp_path / "campaign_summary.json").read_text())
    assert [r["name"] for r in summary["groups"]["groupA"]] == ["s2", "s8"]
    assert not list(gdir.rglob("ckpt-*.msgpack"))  # pruned
    run_campaign.finalize(tmp_path)  # idempotent
    assert (gdir / "report.md").exists()


def test_load_sweep_configs_rejects_duplicates(tmp_path):
    from distributedmnist_tpu.launch.sweep import load_sweep_configs
    (tmp_path / "a.json").write_text(json.dumps({"name": "dup"}))
    (tmp_path / "b.json").write_text(json.dumps({"name": "dup"}))
    with pytest.raises(ValueError):
        load_sweep_configs(tmp_path)


def test_repo_sweep_configs_all_parse():
    """Every shipped config must load cleanly — the grid in configs/
    AND every config in subdirectories (configs/repro/…), so a broken
    repro config can't hide from CI behind the non-recursive sweep
    loader."""
    from pathlib import Path
    from distributedmnist_tpu.launch.sweep import load_sweep_configs
    root = Path(__file__).resolve().parent.parent / "configs"
    cfgs = load_sweep_configs(root)
    assert len(cfgs) >= 15
    modes = {c.sync.mode for c in cfgs}
    assert {"quorum", "interval", "cdf", "sync", "timeout"} <= modes
    # configs/cluster/ holds LocalClusterConfig / FaultPlan JSONs, not
    # experiment configs — their parse coverage lives in
    # test_cluster_exec.py::test_repo_cluster_configs_parse
    subdir_cfgs = [load_sweep_configs(f)[0]
                   for sub in sorted(p for p in root.iterdir()
                                     if p.is_dir() and p.name != "cluster")
                   for f in sorted(sub.glob("*.json"))]
    names = {c.name for c in subdir_cfgs}
    assert "mnist_99" in names  # the one-command 99% repro config


def _jax_can_resize_cpu_mesh() -> bool:
    """Post-init CPU-device-count changes need the jax_num_cpu_devices
    knob (jax ≥ 0.4.38); older jax degrades gracefully to the ambient
    mesh (simulate_devices documents this), so the strict resize
    assertion below is version-gated."""
    import jax
    try:
        jax.config.jax_num_cpu_devices  # noqa: B018
        return True
    except AttributeError:
        return False


@pytest.mark.skipif(not _jax_can_resize_cpu_mesh(),
                    reason="this jax cannot resize the CPU mesh post-init "
                           "(no jax_num_cpu_devices)")
def test_sweep_restores_ambient_mesh(tmp_path):
    """A sweep mixing a simulated-mesh config with ambient-mesh ones
    must run each on ITS mesh: the 4-device config forces 4 virtual
    devices, and the following plain config gets the ambient 8 back
    (ensure_mesh). Without the restore, every config after a
    quorum50-style entry silently runs (and records) wide experiments
    under its narrow name. Subprocess: clear_backends would invalidate
    this session's device handles."""
    import subprocess
    import sys
    script = f"""
import json
from distributedmnist_tpu.core.mesh import simulate_devices
simulate_devices(8)  # the ambient mesh (what conftest does)
from distributedmnist_tpu.core.config import ExperimentConfig
from distributedmnist_tpu.launch.sweep import run_sweep
base = {{"data": {{"dataset": "synthetic", "batch_size": 64,
                   "synthetic_train_size": 256, "synthetic_test_size": 128,
                   "use_native_pipeline": False}},
         "model": {{"compute_dtype": "float32"}},
         "train": {{"max_steps": 2, "log_every_steps": 1,
                    "save_interval_steps": 0, "save_results_period": 0}}}}
cfgs = [ExperimentConfig.from_dict(dict(base, name="sim4",
                                        mesh={{"simulate_devices": 4}})),
        ExperimentConfig.from_dict(dict(base, name="ambient"))]
recs = run_sweep(cfgs, r"{tmp_path}")
print(json.dumps([[r["name"], r["num_replicas"]] for r in recs]))
"""
    out = subprocess.run([sys.executable, "-c", script], capture_output=True,
                         text=True, timeout=420)
    assert out.returncode == 0, out.stderr[-2000:]
    got = json.loads(out.stdout.strip().splitlines()[-1])
    assert got == [["sim4", 4], ["ambient", 8]], got


def test_ensure_mesh_noop_and_nonrestorable():
    """ensure_mesh: matching device set → no backend teardown (device
    objects stay valid); ambient-non-CPU + mismatch → loud error, never
    a silent wrong-mesh run."""
    import jax
    from distributedmnist_tpu.core import mesh as mesh_mod

    devs_before = jax.devices()
    mesh_mod.ensure_mesh(8)   # conftest mesh is already 8 CPU devices
    mesh_mod.ensure_mesh(0)   # ambient == current → noop
    assert jax.devices() == devs_before  # no clear_backends happened

    saved = mesh_mod._ambient_mesh
    try:
        # simulate a process whose ambient backend was a real TPU: a
        # restore to ambient cannot re-force an accelerator
        mesh_mod._ambient_mesh = (1, "tpu")
        with pytest.raises(RuntimeError, match="own process"):
            mesh_mod.ensure_mesh(0)
    finally:
        mesh_mod._ambient_mesh = saved


def test_campaign_groups_resolve_to_configs():
    """Every name the campaign driver would run must resolve to a
    loadable config — including repro_mnist99, whose config lives in
    configs/repro/ (the same fallback run_group applies)."""
    from pathlib import Path
    from distributedmnist_tpu.core.config import ExperimentConfig
    from distributedmnist_tpu.launch.campaign import (EVALUATED_RUNS, GROUPS,
                                                      resolve_config_path)
    root = Path(__file__).resolve().parent.parent / "configs"
    all_names = set()
    for names in GROUPS.values():
        for name in names:
            cfg = ExperimentConfig.from_file(resolve_config_path(root, name))
            assert cfg.name == name
            all_names.add(name)
    assert "mnist_99" in all_names
    assert EVALUATED_RUNS <= all_names  # evaluator targets are real runs


def test_cli_devices(capsys):
    from distributedmnist_tpu.launch.__main__ import main
    main(["devices"])
    out = json.loads(capsys.readouterr().out)
    assert out["process_count"] == 1
    assert len(out["devices"]) == 8


def test_cli_train_with_overrides(tmp_path, capsys):
    from distributedmnist_tpu.launch.__main__ import main
    main(["train",
          "data.dataset=synthetic", "data.batch_size=64",
          "data.synthetic_train_size=512", "data.synthetic_test_size=128",
          "model.compute_dtype=float32",
          "train.max_steps=6", "train.log_every_steps=3",
          f"train.train_dir={tmp_path}/t", "train.save_interval_steps=0"])
    out = json.loads(capsys.readouterr().out.strip().split("\n")[-1])
    assert out["summary"]["final_step"] == 6
    assert "accuracy" in out["test"]


def test_fetch_dry_run_plans_without_network(tmp_path, capsys):
    """`launch fetch --dry-run` prints the full verify/fetch plan —
    files, mirrors, pinned digests, cache status — with zero network or
    cache mutation (the real-data readiness check, ≙ the reference's
    maybe_download at src/mnist_data.py:176-187)."""
    import json as _json
    from distributedmnist_tpu.data.fixtures import materialize_idx_fixture
    from distributedmnist_tpu.launch.__main__ import main

    d = tmp_path / "cache"
    materialize_idx_fixture(d, num_train=64, num_test=32)
    before = sorted(p.name for p in d.iterdir())
    main(["fetch", "--dataset", "mnist", "--data-dir", str(d), "--dry-run"])
    plan = _json.loads(capsys.readouterr().out)
    assert plan["dataset"] == "mnist"
    assert len(plan["plan"]) == 4
    for entry in plan["plan"]:
        assert entry["pinned_sha256"]          # all four MNIST pins exist
        assert entry["mirrors"]
        # the fixture cache is either uncompressed (not verifiable) or
        # a .gz whose digest differs from the real pins - both non-verified
        assert entry["status"] != "verified"
    assert sorted(p.name for p in d.iterdir()) == before   # untouched


def test_fetch_offline_leaves_fixture_cache_intact(tmp_path, capsys):
    """Without egress, `fetch --verify` must fail loudly (exit 1) and
    restore the quarantined fixture files — fixture runs keep working."""
    import json as _json
    import pytest as _pytest
    from distributedmnist_tpu.data import datasets as DS
    from distributedmnist_tpu.data.fixtures import materialize_idx_fixture
    from distributedmnist_tpu.launch.__main__ import main

    d = tmp_path / "cache"
    materialize_idx_fixture(d, num_train=64, num_test=32)
    before = sorted(p.name for p in d.iterdir())
    # point the mirrors somewhere unreachable without touching the net
    orig = DS._IDX_MIRRORS["mnist"]
    DS._IDX_MIRRORS["mnist"] = [str(tmp_path / "nonexistent") + "/"]
    try:
        with _pytest.raises(SystemExit) as e:
            main(["fetch", "--dataset", "mnist", "--data-dir", str(d),
                  "--verify"])
        assert e.value.code == 1
    finally:
        DS._IDX_MIRRORS["mnist"] = orig
    out = _json.loads(capsys.readouterr().out)
    assert out["ok"] is False
    assert sorted(p.name for p in d.iterdir()) == before
    assert "Fixture dataset" in (d / "PROVENANCE.md").read_text()


def test_fetch_verify_upgrades_fixture_to_real(tmp_path, capsys):
    """The full upgrade flow against a hermetic file:// mirror: fetch
    replaces the fixture with digest-verified archives and rewrites
    PROVENANCE.md to say REAL — the one-command path the day egress
    exists."""
    import gzip
    import hashlib
    import json as _json
    from distributedmnist_tpu.data import datasets as DS
    from distributedmnist_tpu.data.fixtures import materialize_idx_fixture
    from distributedmnist_tpu.launch.__main__ import main

    # the "real" archives: a second fixture, gzipped, served via file://
    mirror = tmp_path / "mirror"
    materialize_idx_fixture(mirror, num_train=96, num_test=48)
    del gzip  # the fixture already writes .gz archives
    pins = {gz.name: hashlib.sha256(gz.read_bytes()).hexdigest()
            for gz in sorted(mirror.glob("*.gz"))}
    assert len(pins) == 4

    d = tmp_path / "cache"
    materialize_idx_fixture(d, num_train=64, num_test=32)
    orig_m, orig_p = DS._IDX_MIRRORS["mnist"], DS._PINNED_SHA256["mnist"]
    DS._IDX_MIRRORS["mnist"] = [mirror.as_uri() + "/"]
    DS._PINNED_SHA256["mnist"] = pins
    try:
        main(["fetch", "--dataset", "mnist", "--data-dir", str(d),
              "--verify"])
    finally:
        DS._IDX_MIRRORS["mnist"] = orig_m
        DS._PINNED_SHA256["mnist"] = orig_p
    out = _json.loads(capsys.readouterr().out)
    assert out["ok"] is True
    assert len(out["verified"]) == 4
    prov = (d / "PROVENANCE.md").read_text()
    assert "Real dataset" in prov and "sha256" in prov
    # the installed archives are the mirror's, digest-verified
    for name, digest in pins.items():
        got = hashlib.sha256((d / name).read_bytes()).hexdigest()
        assert got == digest


def test_fetch_rolls_back_downloads_into_empty_slots(tmp_path, capsys):
    """A failed fetch must also delete archives it downloaded into
    slots that were EMPTY beforehand (no quarantine entry to displace)
    — otherwise a real 96-row train-images coexists with the 64-row
    fixture labels and the next fixture run crashes on count mismatch."""
    import json as _json
    import pytest as _pytest
    from distributedmnist_tpu.data import datasets as DS
    from distributedmnist_tpu.data.fixtures import materialize_idx_fixture
    from distributedmnist_tpu.launch.__main__ import main
    import hashlib

    mirror = tmp_path / "mirror"
    materialize_idx_fixture(mirror, num_train=96, num_test=48)
    pins = {gz.name: hashlib.sha256(gz.read_bytes()).hexdigest()
            for gz in sorted(mirror.glob("*.gz"))}
    (mirror / "train-labels-idx1-ubyte.gz").unlink()  # mirror 404s labels

    d = tmp_path / "cache"
    materialize_idx_fixture(d, num_train=64, num_test=32)
    (d / "train-images-idx3-ubyte.gz").unlink()  # empty slot pre-fetch
    before = {p.name: p.read_bytes() for p in d.iterdir()}
    orig_m, orig_p = DS._IDX_MIRRORS["mnist"], DS._PINNED_SHA256["mnist"]
    DS._IDX_MIRRORS["mnist"] = [mirror.as_uri() + "/"]
    DS._PINNED_SHA256["mnist"] = pins
    try:
        with _pytest.raises(SystemExit):
            main(["fetch", "--dataset", "mnist", "--data-dir", str(d),
                  "--verify"])
    finally:
        DS._IDX_MIRRORS["mnist"] = orig_m
        DS._PINNED_SHA256["mnist"] = orig_p
    assert _json.loads(capsys.readouterr().out)["ok"] is False
    after = {p.name: p.read_bytes() for p in d.iterdir()}
    assert after == before  # the downloaded train-images is GONE


def test_fetch_does_not_relabel_unverified_cache_as_real(tmp_path, capsys):
    """`fetch` (no --verify) over a cache of unpinnable idx files must
    not rewrite PROVENANCE.md: nothing was downloaded or verified, so
    claiming 'Real dataset / Downloaded and installed' would let the
    99% oracle run on synthetic pixels labeled as real."""
    import json as _json
    from distributedmnist_tpu.data.fixtures import materialize_idx_fixture
    from distributedmnist_tpu.launch.__main__ import main

    d = tmp_path / "cache"
    materialize_idx_fixture(d, num_train=64, num_test=32, gzip_files=False)
    prov_before = (d / "PROVENANCE.md").read_text()
    assert "Fixture dataset" in prov_before
    main(["fetch", "--dataset", "mnist", "--data-dir", str(d)])
    out = _json.loads(capsys.readouterr().out)
    assert out["ok"] is True
    assert out["downloaded"] == []
    assert out["provenance_updated"] is False
    assert (d / "PROVENANCE.md").read_text() == prov_before


def test_fetch_recovers_stranded_quarantine(tmp_path, capsys):
    """A crash between quarantine and restore leaves *.quarantine files
    behind; the next fetch must put them back (slot empty) or discard
    them (slot re-filled) before planning — an offline box must never
    need manual renames to get its fixture cache working again."""
    import json as _json
    import pytest as _pytest
    from distributedmnist_tpu.data import datasets as DS
    from distributedmnist_tpu.data.fixtures import materialize_idx_fixture
    from distributedmnist_tpu.launch.__main__ import main

    d = tmp_path / "cache"
    materialize_idx_fixture(d, num_train=64, num_test=32)
    before = sorted(p.name for p in d.iterdir())
    # simulate the interrupted run: one slot stranded mid-quarantine
    gz = d / "train-images-idx3-ubyte.gz"
    gz.rename(gz.with_name(gz.name + ".quarantine"))

    # dry-run only REPORTS (no mutation promised) — and its plan must
    # say the slot will be recovered, not claim a download is needed
    main(["fetch", "--dataset", "mnist", "--data-dir", str(d), "--dry-run"])
    plan = _json.loads(capsys.readouterr().out)
    assert plan["stranded_quarantine"] == [gz.name + ".quarantine"]
    assert (d / (gz.name + ".quarantine")).exists()
    by_file = {e["file"]: e["status"] for e in plan["plan"]}
    assert "stranded quarantine" in by_file["train-images-idx3-ubyte.gz"]
    assert "missing" not in by_file["train-images-idx3-ubyte.gz"]

    # a real (offline, failing) fetch first repairs the cache
    orig = DS._IDX_MIRRORS["mnist"]
    DS._IDX_MIRRORS["mnist"] = [str(tmp_path / "nonexistent") + "/"]
    try:
        with _pytest.raises(SystemExit):
            main(["fetch", "--dataset", "mnist", "--data-dir", str(d),
                  "--verify"])
    finally:
        DS._IDX_MIRRORS["mnist"] = orig
    capsys.readouterr()
    assert sorted(p.name for p in d.iterdir()) == before  # fully restored


def test_fetch_partial_mirror_failure_is_transactional(tmp_path, capsys):
    """If only some archives download, fetch --verify must roll the
    cache back EXACTLY to its pre-fetch state (no mixed real/fixture
    cache that would crash the loader on count mismatches)."""
    import hashlib
    import json as _json
    import pytest as _pytest
    from distributedmnist_tpu.data import datasets as DS
    from distributedmnist_tpu.data.fixtures import materialize_idx_fixture
    from distributedmnist_tpu.launch.__main__ import main

    mirror = tmp_path / "mirror"
    materialize_idx_fixture(mirror, num_train=96, num_test=48)
    pins = {gz.name: hashlib.sha256(gz.read_bytes()).hexdigest()
            for gz in sorted(mirror.glob("*.gz"))}
    # the mirror can only serve half the archives
    (mirror / "train-labels-idx1-ubyte.gz").unlink()
    (mirror / "t10k-labels-idx1-ubyte.gz").unlink()

    d = tmp_path / "cache"
    materialize_idx_fixture(d, num_train=64, num_test=32)
    before = {p.name: p.read_bytes() for p in d.iterdir()}
    orig_m, orig_p = DS._IDX_MIRRORS["mnist"], DS._PINNED_SHA256["mnist"]
    DS._IDX_MIRRORS["mnist"] = [mirror.as_uri() + "/"]
    DS._PINNED_SHA256["mnist"] = pins
    try:
        with _pytest.raises(SystemExit):
            main(["fetch", "--dataset", "mnist", "--data-dir", str(d),
                  "--verify"])
    finally:
        DS._IDX_MIRRORS["mnist"] = orig_m
        DS._PINNED_SHA256["mnist"] = orig_p
    out = _json.loads(capsys.readouterr().out)
    assert out["ok"] is False
    after = {p.name: p.read_bytes() for p in d.iterdir()}
    assert after == before      # byte-identical rollback
