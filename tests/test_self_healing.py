"""Trainer-side self-healing: NaN/Inf-guard rollback, preemption
flush + resumable exit code, and the recovery journal they leave
(train/loop.py guards; the cluster-level recovery lives in
test_supervisor.py)."""

import json
import os
import signal
from pathlib import Path

import jax
import numpy as np
import pytest

from conftest import base_config
from distributedmnist_tpu.obsv.journal import load_recovery_events

pytestmark = pytest.mark.tier1


def _trainer(tmp_train_dir, synthetic_datasets, **train_over):
    from distributedmnist_tpu.train.loop import Trainer
    cfg = base_config(train={"train_dir": tmp_train_dir, **train_over})
    return Trainer(cfg, datasets=synthetic_datasets)


def _poison(trainer):
    trainer.state = trainer.state.replace(
        params=jax.tree.map(lambda p: p * np.float32("nan"),
                            trainer.state.params))


def test_nan_guard_rolls_back_to_last_good_checkpoint(tmp_train_dir,
                                                      synthetic_datasets):
    """Params poisoned with NaN mid-run (a bit-flip stand-in): the
    guard detects the nonfinite loss at the next flush, rolls back to
    the newest finite checkpoint, and the run still completes with a
    finite loss — the episode journaled, the poisoned steps absent from
    the train log."""
    t = _trainer(tmp_train_dir, synthetic_datasets,
                 max_steps=12, log_every_steps=2, save_interval_steps=4)
    fired = []

    def cb(step, rec):
        if step == 6 and not fired:
            fired.append(step)
            _poison(t)

    summary = t.run(step_callback=cb)
    assert summary["final_step"] == 12
    assert summary["nan_rollbacks"] == 1
    assert np.isfinite(summary["last_metrics"]["loss"])

    events = load_recovery_events(Path(tmp_train_dir)
                                  / "recovery_journal.jsonl")
    actions = [e["action"] for e in events]
    assert "nonfinite_loss_detected" in actions
    rb = next(e for e in events if e["action"] == "nan_rollback")
    assert rb["to_step"] <= 4 < rb["from_step"]
    # no NaN record ever reached the step log
    log = Path(tmp_train_dir) / "train_log.jsonl"
    losses = [r["loss"] for r in map(json.loads,
                                     log.read_text().splitlines())
              if r.get("event", "step") == "step"]
    assert losses and all(np.isfinite(losses))


@pytest.mark.slow  # a full extra Trainer build (~9 s) for a secondary
# scenario; the primary rollback path stays in tier-1 above
def test_nan_guard_skips_poisoned_checkpoint(tmp_train_dir,
                                             synthetic_datasets):
    """A cadence save can capture the poison before the flush sees it;
    the rollback must skip that checkpoint (params nonfinite) and land
    on the older finite one."""
    t = _trainer(tmp_train_dir, synthetic_datasets,
                 max_steps=12, log_every_steps=6, save_interval_steps=2,
                 async_checkpoint=False)
    fired = []

    def cb(step, rec):
        # flush at step 6 → poison right after; saves at 8, 10, 12
        # capture NaN params, detection only at the step-12 flush
        if step == 6 and not fired:
            fired.append(step)
            _poison(t)

    summary = t.run(step_callback=cb)
    assert summary["final_step"] == 12
    assert summary["nan_rollbacks"] == 1
    events = load_recovery_events(Path(tmp_train_dir)
                                  / "recovery_journal.jsonl")
    assert any(e["action"] == "rollback_candidate_poisoned"
               for e in events)
    rb = next(e for e in events if e["action"] == "nan_rollback")
    assert rb["to_step"] <= 6


def test_multi_rollback_log_splices_gap_and_duplicate_free(
        tmp_train_dir, synthetic_datasets):
    """Satellite: TWO NaN rollbacks in one run still yield a gap-free,
    duplicate-free step sequence after rollback splicing — invariant
    (2) of the chaos checker, driven directly. (Only the
    single-rollback path was covered before; a second rollback crosses
    a window that itself contains replayed records.)"""
    from distributedmnist_tpu.obsv.invariants import (check_metrics_log,
                                                      splice_rollbacks)
    from distributedmnist_tpu.obsv.report import load_jsonl

    t = _trainer(tmp_train_dir, synthetic_datasets,
                 max_steps=16, log_every_steps=2, save_interval_steps=4,
                 nan_guard_max_rollbacks=3, async_checkpoint=False)
    poisoned = []

    def cb(step, rec):
        # first poison detected at the step-8 flush → rollback to 4;
        # second at step 12 lands right before the cadence save, so the
        # rollback must also skip the poisoned step-12 checkpoint
        if step in (6, 12) and step not in poisoned:
            poisoned.append(step)
            _poison(t)

    summary = t.run(step_callback=cb)
    assert summary["final_step"] == 16
    assert summary["nan_rollbacks"] == 2

    recs = load_jsonl(Path(tmp_train_dir) / "train_log.jsonl", "step")
    spliced, rewinds = splice_rollbacks(recs)
    assert rewinds == 2
    assert [r["step"] for r in spliced] == list(range(1, 17))
    # the checker agrees: 2 journaled rollbacks explain both rewinds,
    # the spliced series has no gap and no duplicate
    assert check_metrics_log(recs, allowed_rewinds=2) == []
    events = load_recovery_events(Path(tmp_train_dir)
                                  / "recovery_journal.jsonl")
    assert sum(e["action"] == "nan_rollback" for e in events) == 2
    assert all(np.isfinite(r["loss"]) for r in
               map(json.loads, (Path(tmp_train_dir) / "train_log.jsonl")
                   .read_text().splitlines())
               if r.get("event", "step") == "step")


def test_nan_guard_without_checkpoint_fails_loudly(tmp_train_dir,
                                                   synthetic_datasets):
    t = _trainer(tmp_train_dir, synthetic_datasets,
                 max_steps=10, log_every_steps=2, save_interval_steps=0)
    fired = []

    def cb(step, rec):
        if step == 2 and not fired:
            fired.append(step)
            _poison(t)

    with pytest.raises(RuntimeError, match="no finite checkpoint"):
        t.run(step_callback=cb)


def test_preemption_flushes_checkpoint_and_resumes_exactly(
        tmp_train_dir, synthetic_datasets):
    """SIGTERM mid-run: the loop stops cleanly, the final save runs (a
    flushed checkpoint at the preempted step), and a fresh run resumes
    from EXACTLY that step."""
    from distributedmnist_tpu.train import checkpoint as ckpt

    t = _trainer(tmp_train_dir, synthetic_datasets,
                 max_steps=40, log_every_steps=1, save_interval_steps=0)

    def cb(step, rec):
        if step == 5:
            os.kill(os.getpid(), signal.SIGTERM)

    summary = t.run(step_callback=cb)
    stopped_at = summary["final_step"]
    assert summary["preempted"] == "SIGTERM"
    assert 5 <= stopped_at < 40  # stopped promptly, well short of max
    assert ckpt.latest_checkpoint_step(tmp_train_dir) == stopped_at
    events = load_recovery_events(Path(tmp_train_dir)
                                  / "recovery_journal.jsonl")
    pe = next(e for e in events if e["action"] == "preempt_flush")
    assert pe["signal"] == "SIGTERM" and pe["step"] == stopped_at
    # default SIGTERM disposition restored after run()
    assert signal.getsignal(signal.SIGTERM) == signal.SIG_DFL

    t2 = _trainer(tmp_train_dir, synthetic_datasets,
                  max_steps=stopped_at + 3, log_every_steps=1)
    assert t2._start_step == stopped_at
    s2 = t2.run()
    assert s2["final_step"] == stopped_at + 3 and s2["preempted"] is None


def test_preempted_cli_exits_with_resumable_code(monkeypatch, capsys):
    """The CLI maps a preempted run to train.resumable_exit_code so a
    process supervisor can tell 'resume me' from a crash."""
    from distributedmnist_tpu.launch import __main__ as cli

    class StubTrainer:
        def __init__(self, cfg):
            self.cfg = cfg

        def run(self):
            return {"final_step": 7, "preempted": "SIGTERM", "timing": {}}

        def evaluate(self, split):  # pragma: no cover — must not run
            raise AssertionError("evaluate must be skipped on preemption")

    import distributedmnist_tpu.train.loop as loop_mod
    monkeypatch.setattr(loop_mod, "Trainer", StubTrainer)
    with pytest.raises(SystemExit) as exc:
        cli.main(["train", "mesh.simulate_devices=8",
                  "train.resumable_exit_code=73"])
    assert exc.value.code == 73
    out = json.loads(capsys.readouterr().out)
    assert out["summary"]["preempted"] == "SIGTERM"


# ---------------------------------------------------------------------------
# acceptance e2e: a REAL `launch train` process SIGTERMed mid-run exits
# with the resumable code, leaving a flushed checkpoint a fresh process
# resumes from exactly (slow: boots jax twice)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_sigterm_real_process_exits_resumable_and_resumes(tmp_path):
    import subprocess
    import sys
    import time

    from distributedmnist_tpu.core.mesh import strip_forced_platform_env
    from distributedmnist_tpu.train import checkpoint as ckpt

    repo_root = Path(__file__).resolve().parents[1]
    env = strip_forced_platform_env(dict(os.environ))
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(repo_root)
    argv = [sys.executable, "-m", "distributedmnist_tpu.launch", "train",
            f"train.train_dir={tmp_path}", "data.dataset=synthetic",
            "data.batch_size=16", "data.synthetic_train_size=64",
            "data.synthetic_test_size=32", "model.compute_dtype=float32",
            "train.max_steps=500", "train.log_every_steps=1",
            "train.save_interval_steps=0", "train.save_results_period=0"]
    p = subprocess.Popen(argv, env=env, cwd=repo_root,
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True)
    log = tmp_path / "train_log.jsonl"
    deadline = time.monotonic() + 240
    try:
        while time.monotonic() < deadline:
            if log.exists() and len(log.read_text().splitlines()) >= 3:
                break
            assert p.poll() is None, p.stdout.read()
            time.sleep(0.5)
        else:
            raise AssertionError("worker never started logging")
        p.send_signal(signal.SIGTERM)
        rc = p.wait(timeout=240)
    finally:
        if p.poll() is None:
            p.kill()
    assert rc == 75, p.stdout.read()

    stopped_at = ckpt.latest_checkpoint_step(tmp_path)
    assert stopped_at and stopped_at >= 3  # the preempt flush landed

    # fresh process resumes from EXACTLY that step and runs to its goal
    argv2 = [a for a in argv if not a.startswith("train.max_steps=")]
    argv2.append(f"train.max_steps={stopped_at + 3}")
    out = subprocess.run(argv2, env=env, cwd=repo_root, capture_output=True,
                         text=True, timeout=240)
    assert out.returncode == 0, out.stdout + out.stderr
    assert f"resumed from checkpoint step={stopped_at}" in out.stderr
    summary = json.loads(out.stdout.strip().splitlines()[-1])
    assert summary["summary"]["final_step"] == stopped_at + 3
