"""DevicePrefetcher: the dispatch-ahead input stage (ISSUE 2).

Determinism is load-bearing — the CDF/quorum experiments replay the
same batch stream under either feed — so the contract tested here is
exact: byte-identical batch order vs the synchronous path, checkpoint
cursor of the last *consumed* (not produced) batch, restore that drops
read-ahead, and a producer that joins cleanly when the consumer raises
mid-stream or the loop exits."""

import threading
import time

import numpy as np
import pytest

from conftest import base_config
from distributedmnist_tpu.data.datasets import ArrayDataset, make_synthetic
from distributedmnist_tpu.data.device_prefetch import DevicePrefetcher
from distributedmnist_tpu.data.pipeline import BatchIterator
from distributedmnist_tpu.train.loop import Trainer


def _dataset(n=48):
    images = np.arange(n, dtype=np.float32)[:, None, None, None] * np.ones(
        (3, 3, 1), np.float32)
    return ArrayDataset(images, np.arange(n, dtype=np.int32))


def _host_put(batch):
    """Identity staging: the queue/thread mechanics under test are
    independent of where the batch lands."""
    return {k: np.asarray(v) for k, v in batch.items()}


def test_byte_identical_sequence_vs_sync(topo8):
    """Prefetch-feed == sync-feed, batch for batch, across epoch
    reshuffles, with the real device_put_batch staging."""
    ds = _dataset()
    sync = BatchIterator(ds, batch_size=8, seed=7)
    pf = DevicePrefetcher(BatchIterator(ds, batch_size=8, seed=7),
                          put=topo8.device_put_batch, depth=3)
    with pf:
        for _ in range(20):  # 48/8 = 6 batches/epoch → 3+ epochs
            want = next(sync)
            got = next(pf)
            np.testing.assert_array_equal(np.asarray(got["image"]),
                                          want["image"])
            np.testing.assert_array_equal(np.asarray(got["label"]),
                                          want["label"])


def test_state_is_last_consumed_not_produced():
    """With depth batches staged ahead, state() must still report the
    consumer's cursor — resuming from it replays exactly the batches
    the step never saw."""
    it = BatchIterator(_dataset(96), batch_size=8, seed=1)
    pf = DevicePrefetcher(it, put=_host_put, depth=4)
    consumed = [next(pf) for _ in range(3)]
    # let the producer run ahead to a full queue (bounded wait: a dead
    # producer must fail the test, not hang the suite)
    deadline = time.monotonic() + 10.0
    while pf.qsize < 4:
        assert time.monotonic() < deadline, "producer never filled the queue"
        threading.Event().wait(0.01)
    st = pf.state()
    # core cursor fields (the state also carries the world/batches
    # coordinates the elastic cross-world reassignment reads)
    assert (st["impl"], st["epoch"], st["pos"]) == ("numpy", 0, 24)
    assert it.state()["pos"] > st["pos"]  # producer genuinely read ahead

    fresh = BatchIterator(_dataset(96), batch_size=8, seed=1)
    fresh.restore(st)
    with pf:
        for _ in range(6):
            np.testing.assert_array_equal(np.asarray(next(pf)["label"]),
                                          next(fresh)["label"])
    del consumed


def test_restore_mid_epoch_round_trip():
    pf = DevicePrefetcher(BatchIterator(_dataset(), batch_size=8, seed=3),
                          put=_host_put, depth=2)
    for _ in range(4):
        next(pf)
    st = pf.state()
    tail = [np.asarray(next(pf)["label"]) for _ in range(5)]

    pf.restore(st)  # rewind the SAME prefetcher, dropping read-ahead
    assert pf.state() == st
    for want in tail:
        np.testing.assert_array_equal(np.asarray(next(pf)["label"]), want)
    pf.close()


def test_consumer_exception_clean_shutdown():
    """The train loop's finally calls stop() after an exception; the
    producer — possibly parked on a full queue — must join, and the
    inner cursor must re-sync to the consumed position."""
    it = BatchIterator(_dataset(), batch_size=8, seed=5)
    pf = DevicePrefetcher(it, put=_host_put, depth=2)
    try:
        next(pf)
        next(pf)
        raise RuntimeError("consumer blew up mid-stream")
    except RuntimeError:
        pf.stop()
    assert pf._thread is None or not pf._thread.is_alive()
    assert it.state() == pf.state()
    assert (pf.state()["epoch"], pf.state()["pos"]) == (0, 16)
    # stop() is resumable: the stream continues with batch 3
    ref = BatchIterator(_dataset(), batch_size=8, seed=5)
    ref.restore({"impl": "numpy", "epoch": 0, "pos": 16})
    np.testing.assert_array_equal(np.asarray(next(pf)["label"]),
                                  next(ref)["label"])
    pf.close()
    with pytest.raises(RuntimeError, match="closed"):
        next(pf)
    pf.close()  # idempotent


def test_producer_error_surfaces_in_consumer():
    class Broken:
        def __init__(self):
            self.n = 0

        def __iter__(self):
            return self

        def __next__(self):
            self.n += 1
            if self.n > 2:
                raise ValueError("host loader died")
            return {"image": np.zeros((2, 1)), "label": np.zeros(2)}

    pf = DevicePrefetcher(Broken(), put=_host_put, depth=2)
    next(pf)
    next(pf)
    with pytest.raises(ValueError, match="host loader died"):
        next(pf)
    assert not pf._thread.is_alive() if pf._thread else True


def test_finite_stream_raises_stopiteration():
    batches = iter([{"image": np.full((2, 1), i), "label": np.full(2, i)}
                    for i in range(3)])
    pf = DevicePrefetcher(batches, put=_host_put, depth=2)
    got = [float(next(pf)["label"][0]) for _ in range(3)]
    assert got == [0.0, 1.0, 2.0]
    with pytest.raises(StopIteration):
        next(pf)
    pf.close()


def test_trainer_loss_series_identical_prefetch_vs_sync(tmp_path, monkeypatch):
    """ISSUE 2 acceptance: equal seed → the prefetch path yields the
    exact same loss series as the synchronous path."""
    import os

    monkeypatch.setattr(os, "cpu_count", lambda: 4)  # defeat 1-core gate
    series = {}
    for feed, on in (("prefetch", True), ("sync", False)):
        cfg = base_config(
            data={"device_prefetch": on},
            train={"max_steps": 8, "log_every_steps": 2,
                   "train_dir": str(tmp_path / feed), "resume": False},
        )
        losses = []
        tr = Trainer(cfg)
        assert isinstance(tr.train_feed, DevicePrefetcher) is on
        summary = tr.run(step_callback=lambda s, rec: losses.append(
            (s, rec["loss"], rec["train_acc"])))
        assert summary["final_step"] == 8
        series[feed] = losses
        if on:
            assert "prefetch_queue_depth" in summary["timing"]
        else:
            assert "prefetch_queue_depth" not in summary["timing"]
    assert series["prefetch"] == series["sync"]


def test_trainer_checkpoint_resume_through_prefetcher(tmp_path, monkeypatch):
    """Mid-epoch save via the prefetching feed, then resume: the
    resumed stream must replay from the consumed cursor, producing the
    same state as one uninterrupted run."""
    import os

    monkeypatch.setattr(os, "cpu_count", lambda: 4)  # defeat 1-core gate
    common = dict(
        data={"device_prefetch": True, "batch_size": 64},
        sync={"mode": "sync"},
    )
    cfg = base_config(
        train={"max_steps": 6, "log_every_steps": 3, "save_interval_steps": 3,
               "train_dir": str(tmp_path / "run"), "resume": True},
        **common)
    tr = Trainer(cfg)
    tr.run()
    consumed = tr.train_feed.state()
    assert consumed == tr.train_iter.state()  # stop() re-synced the inner

    tr2 = Trainer(cfg.override({"train.max_steps": 10}))
    assert tr2._start_step == 6
    assert tr2.train_feed.state() == consumed
    losses = []
    tr2.run(step_callback=lambda s, rec: losses.append((s, rec["loss"])))

    cfg_straight = base_config(
        train={"max_steps": 10, "log_every_steps": 3,
               "save_interval_steps": 0,
               "train_dir": str(tmp_path / "straight"), "resume": False},
        **common)
    straight = []
    Trainer(cfg_straight).run(
        step_callback=lambda s, rec: straight.append((s, rec["loss"])))
    assert losses == [x for x in straight if x[0] > 6]


def test_eval_staged_path_matches_inline(topo8):
    """run_full_eval through the DevicePrefetcher == inline staging."""
    from distributedmnist_tpu.train.evaluation import run_full_eval

    cfg = base_config()
    tr = Trainer(cfg, topo=topo8, datasets=make_synthetic(512, 256))
    inline = run_full_eval(tr.eval_fn, tr.state.params, topo8,
                           tr.datasets.test, batch_size=64, prefetch_depth=0)
    staged = run_full_eval(tr.eval_fn, tr.state.params, topo8,
                           tr.datasets.test, batch_size=64, prefetch_depth=3)
    assert staged["num_examples"] == inline["num_examples"] == 256
    assert staged["accuracy"] == inline["accuracy"]
    assert staged["loss"] == inline["loss"]
