"""Mask-math unit tests on the simulated 8-device mesh (SURVEY §4
"implication": test psum semantics without a TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from distributedmnist_tpu.ops.masked_psum import masked_mean_psum

pytestmark = pytest.mark.tier1


def run_sharded(topo, fn, *args, in_specs, out_specs):
    return jax.jit(jax.shard_map(fn, mesh=topo.mesh, in_specs=in_specs,
                                 out_specs=out_specs))(*args)


def test_all_ones_is_plain_mean(topo8):
    x = jnp.arange(8.0)

    def f(x):
        mean, num = masked_mean_psum(x, jnp.ones(()), "replica")
        return mean, num

    mean, num = run_sharded(topo8, f, x, in_specs=(P("replica"),),
                            out_specs=(P(), P()))
    assert float(num) == 8.0
    np.testing.assert_allclose(np.asarray(mean), np.mean(np.arange(8.0)))


def test_mask_drops_contributions(topo8):
    x = jnp.arange(8.0)
    flags = jnp.array([1, 1, 0, 0, 1, 0, 0, 0], jnp.float32)

    def f(x, fl):
        mean, num = masked_mean_psum(x, fl[0], "replica")
        return mean, num

    mean, num = run_sharded(topo8, f, x, flags, in_specs=(P("replica"), P("replica")),
                            out_specs=(P(), P()))
    assert float(num) == 3.0
    np.testing.assert_allclose(np.asarray(mean), (0 + 1 + 4) / 3.0)


def test_all_masked_gives_zero(topo8):
    x = jnp.arange(8.0) + 5.0
    flags = jnp.zeros(8, jnp.float32)

    def f(x, fl):
        return masked_mean_psum(x, fl[0], "replica")

    mean, num = run_sharded(topo8, f, x, flags, in_specs=(P("replica"), P("replica")),
                            out_specs=(P(), P()))
    assert float(num) == 0.0
    np.testing.assert_allclose(np.asarray(mean), 0.0)


def test_masked_mean_of_pytree(topo8):
    tree = {"a": jnp.arange(8.0), "b": jnp.arange(16.0).reshape(8, 2)}
    flags = jnp.array([1, 0, 1, 0, 1, 0, 1, 0], jnp.float32)

    def f(t, fl):
        mean, num = masked_mean_psum(t, fl[0], "replica")
        return mean, num

    mean, num = run_sharded(
        topo8, f, tree, flags,
        in_specs=({"a": P("replica"), "b": P("replica")}, P("replica")),
        out_specs=(P(), P()))
    assert float(num) == 4.0
    np.testing.assert_allclose(np.asarray(mean["a"]), np.mean([0, 2, 4, 6]))
    np.testing.assert_allclose(np.asarray(mean["b"]).ravel(),
                               np.arange(16).reshape(8, 2)[::2].mean(axis=0))


def test_fractional_flags_weight_contributions(topo8):
    """Flags need not be binary — fractional weights scale contributions."""
    x = jnp.arange(8.0)
    w = jnp.array([1, 2, 3, 0, 0, 0, 0, 0], jnp.float32)

    def f(x, w):
        mean, num = masked_mean_psum(x, w[0], "replica")
        return mean, num

    mean, num = run_sharded(topo8, f, x, w, in_specs=(P("replica"), P("replica")),
                            out_specs=(P(), P()))
    assert float(num) == 6.0
    np.testing.assert_allclose(np.asarray(mean), (0 * 1 + 1 * 2 + 2 * 3) / 6.0)
