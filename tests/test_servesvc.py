"""Serving-tier tests: the replica's robustness contract (admission,
deadlines, digest-verified hot-swap, graceful drain), the failover
client shim, the serving chaos schedule grammar, and the three serving
replay invariants over handcrafted artifacts."""

import json
import shutil
import socket
import threading
import time
from pathlib import Path

import pytest

from conftest import base_config


# ---------------------------------------------------------------------------
# shared publisher: ONE short deterministic training run per module
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def published(tmp_path_factory, synthetic_datasets):
    """A staging dir holding a stream of real checkpoints (steps
    10/20/30) plus the run's config — each test publishes them into
    its own serve dir at its own cadence."""
    staging = tmp_path_factory.mktemp("staging")
    cfg = base_config(train={"train_dir": str(staging), "max_steps": 30,
                             "log_every_steps": 10,
                             "save_interval_steps": 10})
    from distributedmnist_tpu.train.loop import Trainer
    Trainer(cfg, datasets=synthetic_datasets).run()
    steps = sorted(int(p.name[5:13]) for p in staging.glob("ckpt-*.msgpack"))
    assert steps == [10, 20, 30]
    return {"staging": staging, "cfg": cfg, "steps": steps}


def publish_step(staging: Path, serve_dir: Path, step: int,
                 truncate: bool = False) -> None:
    """Copy one staged checkpoint (artifact + digest sidecar) into the
    serve dir and point ``checkpoint.json`` at it. ``truncate`` tears
    the artifact AFTER the copy (sidecar kept intact) — the corrupt-
    publish scenario digest verification must refuse."""
    name = f"ckpt-{step:08d}.msgpack"
    serve_dir.mkdir(parents=True, exist_ok=True)
    shutil.copy2(staging / name, serve_dir / name)
    shutil.copy2(staging / (name + ".sha256"),
                 serve_dir / (name + ".sha256"))
    if truncate:
        data = (serve_dir / name).read_bytes()
        (serve_dir / name).write_bytes(data[:max(1, len(data) // 2)])
    tmp = serve_dir / "checkpoint.json.tmp"
    tmp.write_text(json.dumps({"latest_step": step, "latest_path": name,
                               "written_at": time.time()}))
    tmp.replace(serve_dir / "checkpoint.json")


def make_replica(published, tmp_path, first_step=10, **serve_kw):
    from distributedmnist_tpu.core.config import ServeConfig
    from distributedmnist_tpu.servesvc.server import ServingReplica
    serve_src = tmp_path / "publish"
    publish_step(published["staging"], serve_src, first_step)
    scfg = ServeConfig(poll_secs=0.05, **serve_kw)
    rep = ServingReplica(serve_src, serve_dir=tmp_path / "replica",
                         scfg=scfg, cfg=published["cfg"])
    return rep, serve_src


def raw_request(port: int, payload: dict, timeout=10.0) -> dict:
    with socket.create_connection(("127.0.0.1", port),
                                  timeout=timeout) as conn:
        conn.settimeout(timeout)
        conn.sendall((json.dumps(payload) + "\n").encode())
        buf = b""
        while b"\n" not in buf:
            chunk = conn.recv(65536)
            if not chunk:
                break
            buf += chunk
    return json.loads(buf.decode())


def serve_records(rep) -> list[dict]:
    return [json.loads(l) for l in
            (rep.serve_dir / "serve_log.jsonl").read_text().splitlines()
            if l.strip()]


def sample_input(published):
    from distributedmnist_tpu.servesvc.loadgen import make_input_fn
    shape = (published["cfg"].model.image_size,) * 2 + (1,)
    return make_input_fn(shape, "float32")


# ---------------------------------------------------------------------------
# the replica end-to-end
# ---------------------------------------------------------------------------

def test_serve_responds_and_hot_swaps(published, tmp_path):
    """Requests answer from the digest-verified newest step; a fresh
    publish mid-traffic hot-swaps without dropping anything; swap
    journal is monotone with digests."""
    rep, serve_src = make_replica(published, tmp_path)
    rep.start()
    try:
        make_input = sample_input(published)
        out = raw_request(rep.bound_port, {"id": 1,
                                           "inputs": make_input(1)})
        assert out["status"] == "ok" and out["model_step"] == 10
        assert len(out["probs"]) == 10
        # publish step 20 mid-traffic; keep requesting until the swap
        publish_step(published["staging"], serve_src, 20)
        deadline = time.time() + 30
        got_step = 10
        i = 2
        while got_step < 20 and time.time() < deadline:
            out = raw_request(rep.bound_port, {"id": i,
                                               "inputs": make_input(i)})
            assert out["status"] == "ok"  # zero drops across the swap
            got_step = out["model_step"]
            i += 1
        assert got_step == 20
        recs = serve_records(rep)
        swaps = [r for r in recs if r.get("action") == "weight_swap"]
        assert [s["step"] for s in swaps] == [10, 20]
        assert all(s.get("digest") for s in swaps)
        assert all(isinstance(s.get("swap_ms"), float) for s in swaps)
    finally:
        rep.stop()
    # server-side exactly-one-terminal bookkeeping
    recs = serve_records(rep)
    admits = sum(1 for r in recs if r.get("action") == "admit")
    responds = sum(1 for r in recs if r.get("action") == "respond")
    rejects = sum(1 for r in recs if r.get("action") == "reject"
                  and r.get("admitted"))
    assert admits == responds + rejects and admits >= 2


def test_serve_skips_corrupt_publish(published, tmp_path):
    """A torn publish (bytes disagree with the digest sidecar) is
    SKIPPED — the replica keeps serving the previous weights, journals
    the fallback, and the next good publish swaps past it. Invariant:
    no response is ever computed from a failed-digest checkpoint."""
    rep, serve_src = make_replica(published, tmp_path)
    rep.start()
    try:
        make_input = sample_input(published)
        publish_step(published["staging"], serve_src, 20, truncate=True)
        # give the follower several polls at the torn artifact
        time.sleep(0.5)
        out = raw_request(rep.bound_port, {"id": 1,
                                           "inputs": make_input(1)})
        assert out["status"] == "ok"
        assert out["model_step"] == 10  # still the last GOOD step
        publish_step(published["staging"], serve_src, 30)
        deadline = time.time() + 30
        while rep.model_step < 30 and time.time() < deadline:
            time.sleep(0.05)
        assert rep.model_step == 30  # skipped 20 entirely
        recs = serve_records(rep)
        assert [r["step"] for r in recs
                if r.get("action") == "weight_swap"] == [10, 30]
        assert any(r.get("action") == "follow_corrupt_checkpoint_fallback"
                   for r in recs), recs
    finally:
        rep.stop()


def test_serve_admission_and_deadline(published, tmp_path):
    """A full queue sheds with a typed ``overloaded`` reject; an
    expired request gets a typed ``deadline_exceeded`` — bounded queue
    and bounded latency, never silence."""
    rep, _ = make_replica(published, tmp_path, queue_depth=1, max_batch=1)
    slow = threading.Event()
    real_predict = rep._predict

    def slow_predict(params, x):
        if slow.is_set():
            time.sleep(0.4)
        return real_predict(params, x)

    rep._predict = slow_predict
    rep.start()
    try:
        make_input = sample_input(published)
        inputs = make_input(0)
        # warm the bucket so the stall below is the sleep, not compile
        assert raw_request(rep.bound_port,
                           {"id": 0, "inputs": inputs})["status"] == "ok"
        slow.set()
        results: list[dict] = []

        def fire(i):
            results.append(raw_request(rep.bound_port,
                                       {"id": i, "inputs": inputs}))

        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(1, 9)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        statuses = {}
        for r in results:
            key = (r["status"], r.get("reason"))
            statuses[key] = statuses.get(key, 0) + 1
        assert statuses.get(("rejected", "overloaded"), 0) >= 1, statuses
        assert statuses.get(("ok", None), 0) >= 1, statuses
        assert len(results) == 8  # every request got SOME terminal answer
        # expired-in-queue: occupy the batcher with a slow in-flight
        # batch, then queue a request whose deadline is shorter than
        # that batch — it must come back as a TYPED deadline reject
        occupier = threading.Thread(target=fire, args=(98,))
        occupier.start()
        time.sleep(0.1)  # the occupier is now inside the slow predict
        out = raw_request(rep.bound_port, {"id": 99, "inputs": inputs,
                                           "deadline_ms": 1})
        occupier.join(timeout=30)
        assert out == {"id": 99, "status": "rejected",
                       "reason": "deadline_exceeded",
                       "model_step": out["model_step"]}
    finally:
        rep.stop()


def test_serve_graceful_stop_sheds_typed(published, tmp_path):
    """Stopping a replica drains its queue with ``shutting_down``
    rejects — the zero-drop contract holds through teardown."""
    rep, _ = make_replica(published, tmp_path, max_batch=1)
    hold = threading.Event()
    real_predict = rep._predict

    def gated(params, x):
        hold.wait(timeout=5)
        return real_predict(params, x)

    rep._predict = gated
    rep.start()
    try:
        make_input = sample_input(published)
        inputs = make_input(0)
        results: list[dict] = []
        threads = [threading.Thread(
            target=lambda i=i: results.append(
                raw_request(rep.bound_port, {"id": i, "inputs": inputs})))
            for i in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.3)  # let them admit while the batcher is gated
        rep.request_stop()
        hold.set()
        for t in threads:
            t.join(timeout=30)
    finally:
        rep.stop()
    assert len(results) == 4
    assert all(r["status"] in ("ok", "rejected") for r in results)
    rejected = [r for r in results if r["status"] == "rejected"]
    assert all(r["reason"] == "shutting_down" for r in rejected)
    recs = serve_records(rep)
    admits = sum(1 for r in recs if r.get("action") == "admit")
    terminals = sum(1 for r in recs if r.get("action") == "respond"
                    or (r.get("action") == "reject" and r.get("admitted")))
    assert admits == terminals


def test_client_fails_over_and_deadline(published, tmp_path):
    """The round-robin shim retries a dead endpoint onto a live one;
    with nothing alive it returns a typed terminal error instead of
    hanging."""
    from distributedmnist_tpu.servesvc.client import ServeClient
    rep, _ = make_replica(published, tmp_path)
    rep.start()
    try:
        make_input = sample_input(published)
        # endpoint 0 is a dead port (bound then closed), endpoint 1 live
        dead = socket.socket()
        dead.bind(("127.0.0.1", 0))
        dead_port = dead.getsockname()[1]
        dead.close()
        client = ServeClient([("127.0.0.1", dead_port),
                              ("127.0.0.1", rep.bound_port)],
                             deadline_s=10.0, max_attempts=4)
        outs = [client.request(make_input(i), request_id=i)
                for i in range(3)]
        assert all(o["status"] == "ok" for o in outs), outs
        nothing = ServeClient([("127.0.0.1", dead_port)],
                              deadline_s=1.0, max_attempts=3)
        out = nothing.request(make_input(0), request_id=0)
        assert out["status"] == "error"
        assert out["reason"] in ("unavailable", "deadline_exceeded")
    finally:
        rep.stop()


# ---------------------------------------------------------------------------
# protocol hardening (ISSUE 19): dedup cache, deadlines, quarantine
# ---------------------------------------------------------------------------

def test_dedup_replay_answers_from_cache(published, tmp_path):
    """A replayed request id (the retry after a reset ate the
    response) is answered from the idempotency cache — journaled as a
    ``dedup_hit`` AFTER the one respond, never a second execution."""
    rep, _ = make_replica(published, tmp_path)
    rep.start()
    try:
        make_input = sample_input(published)
        payload = {"id": "r-7", "inputs": make_input(7)}
        first = raw_request(rep.bound_port, payload)
        replay = raw_request(rep.bound_port, payload)
        assert first["status"] == "ok"
        # byte-identical outcome: same step, same probs, same id
        assert replay == first
        assert rep.dedup_hits == 1
        recs = serve_records(rep)
        acts = [(r["action"], r.get("id")) for r in recs
                if r.get("id") == "r-7"]
        assert acts.count(("respond", "r-7")) == 1
        assert acts.count(("admit", "r-7")) == 1
        i_resp = acts.index(("respond", "r-7"))
        assert ("dedup_hit", "r-7") in acts[i_resp:]
    finally:
        rep.stop()


def test_dedup_cache_bound_evicts_oldest(published, tmp_path):
    """The cache is bounded LRU: past ``dedup_cache_size`` distinct
    ids, the oldest entry is gone and its replay re-executes (a second
    admit+respond, not a hit) — memory stays bounded under churn."""
    rep, _ = make_replica(published, tmp_path, dedup_cache_size=2)
    rep.start()
    try:
        make_input = sample_input(published)
        for i in range(3):  # id 0 evicted when id 2 lands
            raw_request(rep.bound_port,
                        {"id": i, "inputs": make_input(i)})
        out = raw_request(rep.bound_port,
                          {"id": 0, "inputs": make_input(0)})
        assert out["status"] == "ok"
        assert rep.dedup_hits == 0
        recs = serve_records(rep)
        assert sum(1 for r in recs if r.get("action") == "respond"
                   and r.get("id") == 0) == 2
    finally:
        rep.stop()


def test_slowloris_aborted_while_siblings_served(published, tmp_path):
    """A peer trickling a half request (and one sending nothing: the
    half-open case) costs ONE bounded stall of conn_read_timeout_s on
    its own connection thread — journaled ``conn_abort``, no terminal
    owed, and concurrent well-formed requests keep flowing."""
    rep, _ = make_replica(published, tmp_path, conn_read_timeout_s=0.5)
    rep.start()
    try:
        make_input = sample_input(published)
        slow = socket.create_connection(("127.0.0.1", rep.bound_port),
                                        timeout=10.0)
        slow.sendall(b'{"id": 99, "inp')   # never finishes the line
        half_open = socket.create_connection(
            ("127.0.0.1", rep.bound_port), timeout=10.0)
        # while both stalls are pending, the replica still serves
        out = raw_request(rep.bound_port,
                          {"id": 1, "inputs": make_input(1)})
        assert out["status"] == "ok"
        deadline = time.time() + 10.0
        reasons: set = set()
        while len(reasons) < 2 and time.time() < deadline:
            reasons = {r.get("reason") for r in serve_records(rep)
                       if r.get("action") == "conn_abort"}
            time.sleep(0.05)
        assert reasons == {"read_deadline", "half_open"}
        # the aborted sockets are really closed, not leaked
        slow.settimeout(2.0)
        assert slow.recv(4096) == b""
        slow.close()
        half_open.close()
        # no terminal was owed: admit/terminal books still balance
        recs = serve_records(rep)
        admits = sum(1 for r in recs if r.get("action") == "admit")
        responds = sum(1 for r in recs if r.get("action") == "respond")
        assert admits == responds
    finally:
        rep.stop()


def test_client_quarantines_dead_endpoint(published, tmp_path):
    """After a failed attempt the client benches that endpoint with
    seeded jittered backoff — follow-up requests go straight to the
    live sibling (attempts == 1) instead of re-dialing the corpse —
    and the outcome records carry the attempt books."""
    from distributedmnist_tpu.servesvc.client import ServeClient
    rep, _ = make_replica(published, tmp_path)
    rep.start()
    try:
        make_input = sample_input(published)
        dead = socket.socket()
        dead.bind(("127.0.0.1", 0))
        dead_port = dead.getsockname()[1]
        dead.close()
        client = ServeClient([("127.0.0.1", dead_port),
                              ("127.0.0.1", rep.bound_port)],
                             deadline_s=10.0, max_attempts=4,
                             quarantine_s=30.0, seed=3)
        out = client.request(make_input(0), request_id=0)
        assert out["status"] == "ok"
        if out["attempts"] > 1:     # the dead endpoint was tried first
            assert out["retried"] is True
        assert client.quarantined() == [("127.0.0.1", dead_port)]
        # benched: the next requests never pay the dead dial again
        for i in range(1, 4):
            out = client.request(make_input(i), request_id=i)
            assert out["status"] == "ok" and out["attempts"] == 1
            assert out["retried"] is False
    finally:
        rep.stop()


# ---------------------------------------------------------------------------
# quantized precision tiers (serve.precision_tier + the quant sidecar)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def quant_published(tmp_path_factory, synthetic_datasets):
    """Like ``published`` but the trainer also writes int8 sidecars —
    the tier-preference scenarios publish from here."""
    staging = tmp_path_factory.mktemp("qstaging")
    cfg = base_config(train={"train_dir": str(staging), "max_steps": 30,
                             "log_every_steps": 10,
                             "save_interval_steps": 10},
                      quant={"publish_tiers": "int8",
                             "calibration_examples": 32})
    from distributedmnist_tpu.train.loop import Trainer
    Trainer(cfg, datasets=synthetic_datasets).run()
    return {"staging": staging, "cfg": cfg}


def publish_quant_step(staging: Path, serve_dir: Path, step: int,
                       with_sidecar: bool = True,
                       tear_sidecar: bool = False) -> None:
    """publish_step plus the quant sidecar family; ``tear_sidecar``
    truncates the sidecar AFTER the copy (its digest stays intact) —
    the torn-sidecar scenario digest verification must refuse.

    The sidecar lands BEFORE the pointer flip (publish_step): a
    fast-polling follower that reads the pointer the instant it moves
    must find the sidecar already there, or this test races — a
    replica that consumes the step through the absent-sidecar fallback
    never re-reads it (by design; journaled), so the expected tier
    would be timing-dependent."""
    if with_sidecar:
        qname = f"ckpt-{step:08d}.quant.msgpack"
        serve_dir.mkdir(parents=True, exist_ok=True)
        shutil.copy2(staging / qname, serve_dir / qname)
        shutil.copy2(staging / (qname + ".sha256"),
                     serve_dir / (qname + ".sha256"))
        if tear_sidecar:
            data = (serve_dir / qname).read_bytes()
            (serve_dir / qname).write_bytes(
                data[:max(1, len(data) // 2)])
    publish_step(staging, serve_dir, step)


def test_int8_tier_preferred_and_meta_reports_it(quant_published,
                                                 tmp_path):
    """A replica on precision_tier=int8 installs the sidecar tier, the
    weight_swap journals tier + source identity, responses carry the
    tier, and the meta probe reports active tier + source digest (what
    loadgen artifacts record a sweep actually measured)."""
    from distributedmnist_tpu.core.config import ServeConfig
    from distributedmnist_tpu.servesvc.server import ServingReplica
    from distributedmnist_tpu.train import checkpoint as ckpt
    serve_src = tmp_path / "publish"
    publish_quant_step(quant_published["staging"], serve_src, 10)
    rep = ServingReplica(serve_src, serve_dir=tmp_path / "replica",
                         scfg=ServeConfig(poll_secs=0.05,
                                          precision_tier="int8"),
                         cfg=quant_published["cfg"])
    rep.start()
    try:
        make_input = sample_input(quant_published)
        out = raw_request(rep.bound_port, {"id": 1,
                                           "inputs": make_input(1)})
        assert out["status"] == "ok" and out["model_step"] == 10
        assert out["tier"] == "int8"
        meta = raw_request(rep.bound_port, {"meta": True})
        assert meta["precision_tier"] == "int8"
        assert meta["active_tier"] == "int8"
        src = ckpt.read_quant_sidecar(serve_src, 10)["meta"][
            "source_params_digest"]
        assert meta["tier_source_digest"] == src
        assert meta["model_digest"] == ckpt.quant_sidecar_digest(
            serve_src, 10)
    finally:
        rep.stop()
    swaps = [r for r in serve_records(rep)
             if r.get("action") == "weight_swap"]
    assert [(s["step"], s["tier"], s["source_artifact"])
            for s in swaps] == [(10, "int8",
                                 "ckpt-00000010.quant.msgpack")]
    assert swaps[0]["source_digest"] == src


def test_torn_sidecar_falls_back_to_fp32_without_wedge(quant_published,
                                                       tmp_path):
    """Satellite: a TORN sidecar journals
    ``follow_quant_sidecar_fallback`` and that publish serves full
    precision — the follower cursor advances (no skip-loop re-read
    wedge), and the NEXT good publish upgrades back to int8."""
    from distributedmnist_tpu.core.config import ServeConfig
    from distributedmnist_tpu.servesvc.server import ServingReplica
    serve_src = tmp_path / "publish"
    publish_quant_step(quant_published["staging"], serve_src, 10,
                       tear_sidecar=True)
    rep = ServingReplica(serve_src, serve_dir=tmp_path / "replica",
                         scfg=ServeConfig(poll_secs=0.05,
                                          precision_tier="int8"),
                         cfg=quant_published["cfg"])
    rep.start()
    try:
        make_input = sample_input(quant_published)
        out = raw_request(rep.bound_port, {"id": 1,
                                           "inputs": make_input(1)})
        assert out["status"] == "ok" and out["model_step"] == 10
        assert out["tier"] == "fp32"  # the fallback, never torn bytes
        # the cursor CONSUMED step 10 through the fp32 path — several
        # polls later there is still exactly ONE fallback journaled
        time.sleep(0.4)
        recs = serve_records(rep)
        fallbacks = [r for r in recs
                     if r.get("action") == "follow_quant_sidecar_fallback"]
        assert len(fallbacks) == 1, fallbacks
        assert fallbacks[0]["step"] == 10
        assert "CheckpointCorruptError" in fallbacks[0]["reason"]
        # a sidecar-less publish falls back too (journaled as absent)…
        publish_quant_step(quant_published["staging"], serve_src, 20,
                           with_sidecar=False)
        deadline = time.time() + 30
        while rep.model_step < 20 and time.time() < deadline:
            time.sleep(0.05)
        assert rep.model_step == 20 and rep.model_tier == "fp32"
        # …and the next GOOD sidecar restores the quantized tier
        publish_quant_step(quant_published["staging"], serve_src, 30)
        while rep.model_step < 30 and time.time() < deadline:
            time.sleep(0.05)
        assert rep.model_step == 30 and rep.model_tier == "int8"
    finally:
        rep.stop()
    recs = serve_records(rep)
    swaps = [(r["step"], r["tier"]) for r in recs
             if r.get("action") == "weight_swap"]
    assert swaps == [(10, "fp32"), (20, "fp32"), (30, "int8")]
    reasons = [r["reason"].split(":")[0] for r in recs
               if r.get("action") == "follow_quant_sidecar_fallback"]
    assert reasons == ["CheckpointCorruptError", "sidecar_absent"]


# ---------------------------------------------------------------------------
# serving chaos schedule grammar
# ---------------------------------------------------------------------------

@pytest.mark.tier1
def test_chaos_serving_tier_payload_wiring():
    """serve_precision_tiers pins replica tiers AND arms the publisher
    with the matching quant.publish_tiers; tier-less configs keep the
    byte-identical historical payloads."""
    from distributedmnist_tpu.launch.chaos import ChaosConfig
    cfg = ChaosConfig(payload="serving", serve_replicas=2,
                      serve_precision_tiers=("int8",))
    cmds = cfg.resolved_worker_commands()
    assert "--precision-tier int8" in cmds["1"]
    assert "--precision-tier" not in cmds["2"]
    assert "quant.publish_tiers=int8" in cfg.resolved_train_command()
    plain = ChaosConfig(payload="serving", serve_replicas=2)
    assert "--precision-tier" not in plain.resolved_worker_commands()["1"]
    assert "quant.publish_tiers" not in plain.resolved_train_command()
    # a typo'd tier fails typed at config build, naming the valid set —
    # not as a replica crash-looping against its restart budget
    from distributedmnist_tpu.launch.cluster import ClusterError
    with pytest.raises(ClusterError, match="in8.*valid tiers"):
        ChaosConfig(payload="serving", serve_precision_tiers=("in8",))


@pytest.mark.tier1
def test_serving_schedule_grammar_and_determinism():
    from distributedmnist_tpu.launch.chaos import generate_serving_schedule
    a = generate_serving_schedule(7, 3, [1, 2], (5, 40), (6, 20))
    b = generate_serving_schedule(7, 3, [1, 2], (5, 40), (6, 20))
    assert a == b  # deterministic in (seed, trial)
    kinds = [(f.kind, f.worker) for f in a.faults]
    # always ≥1 serve-replica kill and EXACTLY one publisher corrupt
    assert any(k == "kill" and w in (1, 2) for k, w in kinds)
    assert kinds.count(("corrupt", 0)) == 1
    # the corrupt is UNPAIRED (no publisher kill in serving mode)
    assert ("kill", 0) not in kinds
    for f in a.faults:
        if f.kind in ("kill", "hang", "stall"):
            assert f.worker in (1, 2)
            assert 5 <= f.step <= 40
        if f.kind == "corrupt":
            assert 6 <= f.step <= 20
    c = generate_serving_schedule(8, 3, [1, 2], (5, 40), (6, 20))
    assert c != a  # seed actually varies the draw


# ---------------------------------------------------------------------------
# the three serving replay invariants over handcrafted artifacts
# ---------------------------------------------------------------------------

def _write_jsonl(path: Path, records: list[dict]) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("".join(json.dumps(r) + "\n" for r in records))


def _serving_trial(tmp_path, *, drop_request=False, vanish_admit=False,
                   faulted=False, swap_after_tear=False,
                   backwards_swap=False) -> Path:
    trial = tmp_path / "trial"
    issues = [{"event": "load", "action": "issue", "id": i, "time": 1.0 + i}
              for i in range(3)]
    outcomes = [{"event": "load", "action": "outcome", "id": i,
                 "status": "ok", "latency_ms": 5.0, "time": 2.0 + i}
                for i in range(3)]
    if drop_request:
        outcomes = outcomes[:-1]
    _write_jsonl(trial / "loadgen.jsonl", issues + outcomes)
    journal = [{"event": "fault", "action": "corrupt_latest_checkpoint",
                "worker": 0, "target": "ckpt-00000020.msgpack",
                "ts": 100.0}]
    if faulted:
        journal.append({"event": "fault", "action": "kill_worker",
                        "worker": 1, "ts": 50.0})
    _write_jsonl(trial / "command_journal.jsonl", journal)
    serve = [{"event": "serve", "action": "weight_swap", "step": 10,
              "digest": "d", "time": 90.0},
             {"event": "serve", "action": "admit", "id": 0, "time": 91.0},
             {"event": "serve", "action": "respond", "id": 0,
              "model_step": 10, "time": 91.5}]
    if vanish_admit:
        serve.append({"event": "serve", "action": "admit", "id": 1,
                      "time": 92.0})  # no terminal for it
    if swap_after_tear:
        serve.append({"event": "serve", "action": "weight_swap",
                      "step": 20, "digest": "d2", "time": 101.0})
    if backwards_swap:
        serve.append({"event": "serve", "action": "weight_swap",
                      "step": 5, "digest": "d0", "time": 102.0})
    _write_jsonl(trial / "worker1" / "serve_log.jsonl", serve)
    (trial / "worker1" / "train_log.jsonl").write_text("")
    return trial


def _check(trial) -> dict:
    from distributedmnist_tpu.obsv.invariants import check_serving
    from distributedmnist_tpu.obsv.report import load_jsonl
    journal = load_jsonl(trial / "command_journal.jsonl")
    violations, applicable, workers, decode_applicable = check_serving(
        trial, {"serve_workers": [1]}, journal)
    return {"violations": violations, "applicable": applicable,
            "workers": workers, "decode_applicable": decode_applicable,
            "by_inv": {v.invariant for v in violations}}


@pytest.mark.tier1
def test_serving_invariants_clean_pass(tmp_path):
    got = _check(_serving_trial(tmp_path))
    assert got["applicable"] and got["workers"] == {1}
    assert got["violations"] == []


@pytest.mark.tier1
def test_serving_invariant_catches_dropped_request(tmp_path):
    got = _check(_serving_trial(tmp_path, drop_request=True))
    assert "serve_outcomes" in got["by_inv"]


@pytest.mark.tier1
def test_serving_invariant_vanished_admit_needs_fault_exemption(tmp_path):
    # an admitted request with no terminal outcome on an UNFAULTED
    # replica is a violation ...
    got = _check(_serving_trial(tmp_path, vanish_admit=True))
    assert "serve_outcomes" in got["by_inv"]
    # ... but on a replica the run killed, the in-flight loss is the
    # fault working (the CLIENT side still reached its outcome)
    got = _check(_serving_trial(tmp_path, vanish_admit=True, faulted=True))
    assert "serve_outcomes" not in got["by_inv"]


@pytest.mark.tier1
def test_serving_invariant_swap_after_tear_fails(tmp_path):
    got = _check(_serving_trial(tmp_path, swap_after_tear=True))
    assert "serve_digest" in got["by_inv"]


@pytest.mark.tier1
def test_serving_invariant_monotone(tmp_path):
    got = _check(_serving_trial(tmp_path, backwards_swap=True))
    assert "serve_monotone" in got["by_inv"]


@pytest.mark.tier1
def test_serving_invariants_skip_for_train_trials(tmp_path):
    from distributedmnist_tpu.obsv.invariants import check_serving
    (tmp_path / "t").mkdir()
    violations, applicable, workers, decode_applicable = check_serving(
        tmp_path / "t", {}, [])
    assert not applicable and not violations and not workers
    assert not decode_applicable


# ---------------------------------------------------------------------------
# mixed-payload cluster + target_worker supervision
# ---------------------------------------------------------------------------

def test_worker_commands_and_target_worker(tmp_path):
    """A mixed roster runs per-worker payloads, and supervision counts
    target progress from the named worker only — worker 1 races far
    past the target while slow worker 0 is what the run waits for."""
    from distributedmnist_tpu.launch.cluster import (LocalClusterConfig,
                                                     LocalProcessCluster)
    from distributedmnist_tpu.launch.exec import CommandExecutor, RetryPolicy
    from distributedmnist_tpu.launch.supervisor import (ClusterSupervisor,
                                                        SupervisorConfig)
    loop = ('i=0; while [ $i -lt {n} ]; do i=$((i+1)); '
            'echo "{{\\"step\\": $i}}" >> train_log.jsonl; '
            'sleep {dt}; done; sleep 60')
    cfg = LocalClusterConfig(
        name="mixed", num_workers=2, workdir=str(tmp_path),
        train_command=loop.format(n=12, dt="0.25"),
        worker_commands={"1": loop.format(n=500, dt="0.01")})
    cluster = LocalProcessCluster(cfg, CommandExecutor(
        journal=cfg.root / "command_journal.jsonl",
        retry=RetryPolicy(max_attempts=1)))
    cluster.create()
    try:
        cluster.run_train()
        sup = ClusterSupervisor(cluster, SupervisorConfig(quorum=1))
        t0 = time.monotonic()
        got = sup.supervise_until_step(10, poll_secs=0.2,
                                       timeout_secs=60.0,
                                       target_worker=0)
        elapsed = time.monotonic() - t0
        # worker 1 blew past 10 almost immediately; the run returned
        # only once WORKER 0 (0.25 s/step) actually got there
        assert got["step"] >= 10
        assert elapsed >= 1.5, elapsed
        prog = cluster.worker_progress()
        # the fast payload really ran ITS OWN command, well past the
        # target worker 0 was held to (loose bound: 1-core box)
        assert prog[1] > 3 * got["step"], prog
    finally:
        cluster.kill_all()
        cluster.exec.close()


# ---------------------------------------------------------------------------
# the acceptance scenario: a seeded serving-mode chaos trial
# ---------------------------------------------------------------------------

@pytest.mark.slow  # boots a publisher + 2 serving replicas + reference (~3 min)
def test_serving_chaos_trial_end_to_end(tmp_path):
    """Replica kill + corrupt published checkpoint under live load:
    the trial completes with all three serving invariants passing and
    the load generator reporting zero dropped requests."""
    from distributedmnist_tpu.launch.chaos import ChaosConfig, run_campaign
    cfg = ChaosConfig(name="servetrial", workdir=str(tmp_path),
                      payload="serving", trials=1, seed=0,
                      until_step=60, save_interval_steps=10,
                      serve_replicas=2, shrink=False,
                      trial_timeout_s=420.0)
    summary = run_campaign(cfg)
    assert summary["trials"] == 1
    assert summary["all_green"], summary
    inv = summary["invariants"]
    for name in ("serve_outcomes", "serve_digest", "serve_monotone"):
        assert inv[name]["pass"] == 1, (name, inv)
    sv = summary["serving"]
    assert sv["issued"] > 0 and sv["dropped"] == 0, sv
    assert summary["faults"]["fired"] >= 1, summary["faults"]


@pytest.mark.slow  # boots a publisher + 2 decode replicas + proxies (~4 min)
def test_network_chaos_trial_end_to_end(tmp_path):
    """ISSUE 19 acceptance: transport faults (chaos proxies) under
    live decode load — every scheduled net fault fires, the mandatory
    reset cuts a token stream MID-generation, the partition opens under
    live traffic, zero requests are dropped, and invariant 13 holds
    the exactly-once books."""
    import json as _json
    from distributedmnist_tpu.launch.chaos import ChaosConfig, run_campaign
    cfg = ChaosConfig(name="nettrial", workdir=str(tmp_path),
                      payload="serving", trials=1, seed=0,
                      until_step=60, save_interval_steps=10,
                      serve_replicas=2, serve_decode=True, network=True,
                      shrink=False, trial_timeout_s=420.0)
    summary = run_campaign(cfg)
    assert summary["trials"] == 1
    assert summary["all_green"], summary
    inv = summary["invariants"]
    assert inv["net_faults"]["pass"] == 1, inv
    for name in ("serve_outcomes", "serve_digest", "serve_monotone",
                 "decode_swap"):
        assert inv[name]["pass"] == 1, (name, inv)
    sv = summary["serving"]
    assert sv["issued"] > 0 and sv["dropped"] == 0, sv
    assert summary["faults"]["never_fired"] == 0, summary["faults"]
    net = summary["net"]
    assert net["fired"] >= 2, net
    assert net["faults_by_kind"].get("net_reset") == 1, net
    assert net["faults_by_kind"].get("net_partition") == 1, net
    # the reset's journal record proves the cut was MID-stream (bytes
    # had already flowed) and the partition cut LIVE connections
    recs = [_json.loads(l) for l in
            (tmp_path / "nettrial" / "trial000"
             / "command_journal.jsonl").read_text().splitlines()]
    rst = [r for r in recs if r.get("action") == "net_reset"]
    assert rst and rst[0]["mid_stream"] and rst[0]["bytes_passed"] > 0
