"""Report harness: stats + figures from real trainer/evaluator logs
(≙ the analysis half of tools/benchmark.py, minus the regex scraping —
logs are structured from the start)."""

import json
from pathlib import Path

import numpy as np
import pytest

from conftest import base_config
from distributedmnist_tpu.obsv import report as rpt

pytestmark = pytest.mark.tier1


@pytest.fixture(scope="module")
def run_dirs(tmp_path_factory):
    """One tiny real training run + one evaluator pass."""
    root = tmp_path_factory.mktemp("report_run")
    train_dir = root / "train"
    eval_dir = root / "eval"
    from distributedmnist_tpu.core.config import EvalConfig
    from distributedmnist_tpu.evalsvc import Evaluator
    from distributedmnist_tpu.train.loop import Trainer

    cfg = base_config(
        train={"max_steps": 8, "log_every_steps": 2,
               "save_interval_secs": 0, "save_interval_steps": 8,
               "save_results_period": 8, "train_dir": str(train_dir)})
    Trainer(cfg).run()
    Evaluator(train_dir,
              EvalConfig(eval_dir=str(eval_dir), run_once=True,
                         eval_interval_secs=0.01)).run()
    return train_dir, eval_dir


def test_load_experiment(run_dirs):
    train_dir, eval_dir = run_dirs
    data = rpt.load_experiment(train_dir, eval_dir)
    assert [s["step"] for s in data["steps"]] == list(range(1, 9))
    assert all("time" in s for s in data["steps"])
    assert len(data["evals"]) == 1 and "time" in data["evals"][0]
    assert data["step_times"] is not None and data["step_times"].shape == (8, 8)
    assert data["time_acc"] is not None and data["time_acc"].shape[1] == 4


def test_stats_and_figures(run_dirs, tmp_path):
    train_dir, eval_dir = run_dirs
    stats = rpt.generate_report(train_dir, eval_dir, tmp_path, name="t")
    assert stats["num_steps"] == 8
    assert "barrier" in stats and stats["barrier"]["count"] == 8
    assert len(stats["per_replica"]) == 8
    assert "p99" in stats["per_iteration"]
    assert 0.0 <= stats["final_precision_at_1"] <= 1.0
    saved = json.loads((tmp_path / "stats.json").read_text())
    assert saved["num_steps"] == 8
    for fig in ("step_loss.png", "time_loss.png", "time_step.png",
                "time_precision.png", "replica_time_cdf.png"):
        assert (tmp_path / fig).stat().st_size > 0, fig


def test_load_jsonl_tolerates_torn_tail(tmp_path):
    p = tmp_path / "log.jsonl"
    p.write_text('{"event": "step", "step": 1}\n{"event": "st')
    assert rpt.load_jsonl(p, "step") == [{"event": "step", "step": 1}]


# ---------------------------------------------------------------------------
# tail_records: the ONE torn-tail backward scanner every poll-loop
# reader shares (cluster.parse_poll_output, broker.tail_heartbeat,
# loadgen.read_latest_window) — edge cases live here, once
# ---------------------------------------------------------------------------

def test_tail_records_newest_first_past_torn_tail(tmp_path):
    from distributedmnist_tpu.obsv.journal import tail_records
    p = tmp_path / "log.jsonl"
    p.write_text('{"step": 1}\n{"step": 2}\n{"step": 3, "lo')
    assert [r["step"] for r in tail_records(p)] == [2, 1]
    # same discipline over a pre-captured text tail
    assert [r["step"] for r in tail_records(
        text='{"step": 1}\n{"step": 2}\n{"step": 3, "lo')] == [2, 1]


def test_tail_records_skips_blank_nondict_and_garbage(tmp_path):
    from distributedmnist_tpu.obsv.journal import tail_records
    p = tmp_path / "log.jsonl"
    p.write_text('garbage\n\n[1, 2]\n7\n"str"\n{"ok": 1}\n   \n')
    assert list(tail_records(p)) == [{"ok": 1}]


def test_tail_records_nothing_usable(tmp_path):
    from distributedmnist_tpu.obsv.journal import tail_records
    assert list(tail_records(tmp_path / "missing.jsonl")) == []
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert list(tail_records(empty)) == []
    torn = tmp_path / "torn.jsonl"
    torn.write_text('{"a": \n{"b"')  # every buffered line torn
    assert list(tail_records(torn)) == []
    assert list(tail_records(text="")) == []


def test_tail_records_window_starts_mid_line(tmp_path):
    # a tail_bytes window almost always begins mid-record: the torn
    # HEAD line must be skipped exactly like a torn tail
    from distributedmnist_tpu.obsv.journal import tail_records
    p = tmp_path / "log.jsonl"
    lines = "".join(json.dumps({"step": i, "pad": "x" * 40}) + "\n"
                    for i in range(20))
    p.write_text(lines)
    got = [r["step"] for r in tail_records(p, tail_bytes=200)]
    assert got and got == sorted(got, reverse=True)
    assert 19 in got and 0 not in got  # a real window, torn head dropped


def test_tail_records_requires_exactly_one_source(tmp_path):
    from distributedmnist_tpu.obsv.journal import tail_records
    with pytest.raises(ValueError, match="exactly one"):
        list(tail_records())
    with pytest.raises(ValueError, match="exactly one"):
        list(tail_records(tmp_path / "x", text="{}"))


def test_old_logs_without_time_still_get_step_figures(tmp_path):
    # regression: pre-"time"-field logs must not zero out the report
    train_dir = tmp_path / "train"
    train_dir.mkdir()
    (train_dir / "train_log.jsonl").write_text(
        '{"event": "step", "step": 1, "loss": 1.0, "train_acc": 0.1}\n'
        '{"event": "step", "step": 2, "loss": 0.5, "train_acc": 0.2}\n')
    data = rpt.load_experiment(train_dir)
    written = {p.name for p in rpt.plot_experiment(data, tmp_path / "out")}
    assert written == {"step_loss.png"}  # time-axis figures degrade away


def test_plot_sweep_quorum_axis(tmp_path):
    records = [
        {"name": f"k{k}", "aggregate_k": k, "interval_ms": 0,
         "test_accuracy": 0.9 + 0.01 * k, "examples_per_sec": 100.0 * k,
         "timing": {"per_replica": [{"mean": float(k + i)}
                                    for i in range(4)]}}
        for k in (1, 2, 4)
    ]
    written = rpt.plot_sweep(records, tmp_path)
    names = {p.name for p in written}
    assert names == {"acc_vs_aggregate_k.png", "throughput_vs_aggregate_k.png",
                     "step_time_cdf.png"}


def test_plot_sweep_no_numeric_axis(tmp_path):
    records = [{"name": "a", "aggregate_k": 4, "interval_ms": 0,
                "test_accuracy": 0.9, "examples_per_sec": 10.0,
                "timing": {"per_replica": []}}]
    assert rpt.plot_sweep(records, tmp_path) == []
