"""DP×SP training correctness: the sequence-sharded train step (ring
or ulysses attention + cross-shard token-shift loss + seq-axis gradient
psum) must produce EXACTLY the update a dense single-device step would.
This is the long-context path the reference lacks entirely
(SURVEY §5.7) wired through the real product train step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import base_config
from distributedmnist_tpu.core.mesh import make_topology
from distributedmnist_tpu.core.config import MeshConfig
from distributedmnist_tpu.models import transformer
from distributedmnist_tpu.models.registry import get_model
from distributedmnist_tpu.parallel.api import (build_train_step,
                                               init_train_state)
from distributedmnist_tpu.train.lr_schedule import constant

LR = 0.1


def _cfg(sp_attention, n_replicas, n_seq, heads=4):
    return base_config(
        data={"dataset": "synthetic_lm", "batch_size": 4 * n_replicas},
        model={"name": "transformer", "compute_dtype": "float32",
               "seq_len": 32, "model_dim": 32, "num_heads": heads,
               "num_layers": 2, "vocab_size": 37,
               "attention_impl": "dense", "sp_attention": sp_attention},
        sync={"mode": "sync", "straggler_profile": "none"},
    )


def _tokens(cfg, key=0):
    b, s = cfg.data.batch_size, cfg.model.seq_len
    toks = jax.random.randint(jax.random.PRNGKey(key), (b, s), 0,
                              cfg.model.vocab_size)
    return {"image": toks, "label": toks}


def _dense_reference_update(cfg, batch):
    """Single-device: params - lr * grad(mean-over-batch dense loss)."""
    model = get_model(cfg.model)
    params = model.init(jax.random.PRNGKey(cfg.model.init_seed))

    def loss_fn(p):
        logits = transformer.apply(p, batch["image"],
                                   num_heads=cfg.model.num_heads,
                                   compute_dtype=jnp.float32)
        return transformer.loss_fn(logits, batch["label"])

    loss, grads = jax.value_and_grad(loss_fn)(params)
    new = jax.tree.map(lambda p, g: p - LR * g, params, grads)
    return loss, new


def _sp_update(cfg, batch, n_replicas, n_seq):
    topo = make_topology(MeshConfig(num_replicas=n_replicas,
                                    seq_parallelism=n_seq))
    model = get_model(cfg.model)
    state = topo.device_put_replicated(init_train_state(model, cfg))
    step_fn = build_train_step(model, cfg, topo, constant(LR))
    gbatch = topo.device_put_batch(batch, seq_sharded=True)
    state, metrics = step_fn(state, gbatch)
    return metrics, state.params


@pytest.mark.parametrize("sp_attention,n_replicas,n_seq", [
    ("ring", 2, 4),
    ("ulysses", 2, 4),   # heads=4 divisible by n_seq=4
    ("ring", 1, 8),
])
def test_sp_step_matches_dense_update(sp_attention, n_replicas, n_seq):
    cfg = _cfg(sp_attention, n_replicas, n_seq)
    batch = _tokens(cfg)
    want_loss, want_params = _dense_reference_update(cfg, batch)
    metrics, got_params = _sp_update(cfg, batch, n_replicas, n_seq)

    # loss: mean over replicas of per-replica dense losses == global
    # dense loss (identical row counts)
    np.testing.assert_allclose(float(metrics["loss"]), float(want_loss),
                               rtol=2e-5, atol=2e-5)
    for a, b in zip(jax.tree.leaves(got_params), jax.tree.leaves(want_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_sp_requires_capable_model():
    cfg = _cfg("ring", 2, 4)
    cfg = cfg.override({"model.name": "mnist_cnn", "model.compute_dtype":
                        "float32"})
    topo = make_topology(MeshConfig(num_replicas=2, seq_parallelism=4))
    model = get_model(cfg.model)
    with pytest.raises(ValueError, match="seq_parallelism"):
        build_train_step(model, cfg, topo, constant(LR))


def test_trainer_end_to_end_seq_parallel(tmp_train_dir):
    """Full Trainer on a (replica=2, seq=4) mesh: runs, learns, and the
    quorum discipline still applies on the replica axis."""
    from distributedmnist_tpu.train.loop import Trainer

    cfg = _cfg("ring", 2, 4)
    cfg = cfg.override({
        "mesh.num_replicas": 2, "mesh.seq_parallelism": 4,
        "sync.mode": "quorum", "sync.num_replicas_to_aggregate": 1,
        "sync.straggler_profile": "lognormal",
        "data.use_native_pipeline": True,
        "train.max_steps": 20, "train.train_dir": tmp_train_dir,
        "train.log_every_steps": 10,
    })
    tr = Trainer(cfg)
    summary = tr.run()
    assert summary["final_step"] == 20
    assert summary["last_metrics"]["num_contributors"] == 1.0
    # loss must drop from roughly ln(vocab) chance level
    assert summary["last_metrics"]["loss"] < 3.4
    ev = tr.evaluate("test")
    assert ev["num_examples"] == 256


def test_sharded_paths_refuse_dropout_models():
    """A model that consumes a dropout key must not silently train
    without dropout on the SP path (which does not thread one)."""
    import dataclasses

    cfg = _cfg("ring", 2, 4)
    topo = make_topology(MeshConfig(num_replicas=2, seq_parallelism=4))
    model = dataclasses.replace(get_model(cfg.model), uses_dropout=True)
    with pytest.raises(ValueError, match="dropout"):
        build_train_step(model, cfg, topo, constant(LR))
