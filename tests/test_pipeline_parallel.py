"""Pipeline parallelism correctness: the GPipe-style microbatch
pipeline over the stage axis must match the dense single-device
transformer exactly — forward and one-step update — and compose with
data parallelism through the real Trainer."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from conftest import (LOSS_TOL, assert_update_parity,
                      base_config)
from distributedmnist_tpu.core.config import MeshConfig
from distributedmnist_tpu.core.mesh import make_topology
from distributedmnist_tpu.models import transformer
from distributedmnist_tpu.models.registry import get_model
from distributedmnist_tpu.ops.pipeline import pipeline_apply
from distributedmnist_tpu.parallel.api import (build_train_step,
                                               init_train_state,
                                               state_partition_specs)
from distributedmnist_tpu.train.lr_schedule import constant

LR = 0.1


def test_pipeline_apply_identity_stages():
    """A pipeline of elementwise stage functions == composing them."""
    topo = make_topology(MeshConfig(num_replicas=1, pipeline_parallelism=8))
    axis = topo.stage_axis
    micro = jnp.arange(4 * 2 * 3, dtype=jnp.float32).reshape(4, 2, 3)

    def fn(mb):
        return pipeline_apply(lambda x: x * 2.0 + 1.0, mb, axis)

    out = jax.jit(jax.shard_map(fn, mesh=topo.mesh,
                                in_specs=P(), out_specs=P()))(micro)
    want = micro
    for _ in range(8):
        want = want * 2.0 + 1.0
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), rtol=1e-5)


def _cfg(n_replicas=1, layers=4):
    return base_config(
        data={"dataset": "synthetic_lm", "batch_size": 8 * n_replicas},
        model={"name": "transformer", "compute_dtype": "float32",
               "seq_len": 16, "model_dim": 32, "num_heads": 4,
               "num_layers": layers, "vocab_size": 37,
               "attention_impl": "dense"},
        sync={"mode": "sync", "straggler_profile": "none"},
    )


def _tokens(cfg, key=0):
    b, s = cfg.data.batch_size, cfg.model.seq_len
    toks = jax.random.randint(jax.random.PRNGKey(key), (b, s), 0,
                              cfg.model.vocab_size)
    return {"image": toks, "label": toks}


def _dense_update(cfg, batch):
    model = get_model(cfg.model)
    params = model.init(jax.random.PRNGKey(cfg.model.init_seed))

    def loss_fn(p):
        logits = transformer.apply(p, batch["image"],
                                   num_heads=cfg.model.num_heads,
                                   compute_dtype=jnp.float32)
        return transformer.loss_fn(logits, batch["label"])

    loss, grads = jax.value_and_grad(loss_fn)(params)
    return loss, jax.tree.map(lambda p, g: p - LR * g, params, grads)


@pytest.mark.parametrize("n_replicas,n_stage,n_model,microbatches", [
    (1, 4, 1, 4),
    (2, 4, 1, 2),   # DP × PP
    (1, 2, 1, 1),   # single microbatch (pure layer split)
    (2, 2, 2, 2),   # DP × PP × TP: stage outermost, Megatron inside
    (1, 2, 4, 2),   # PP × wide TP
])
def test_pp_step_matches_dense_update(n_replicas, n_stage, n_model,
                                      microbatches):
    cfg = _cfg(n_replicas=n_replicas)
    cfg = cfg.override({"mesh.num_replicas": n_replicas,
                        "mesh.pipeline_parallelism": n_stage,
                        "mesh.model_parallelism": n_model,
                        "mesh.pipeline_microbatches": microbatches})
    batch = _tokens(cfg)
    want_loss, want_params = _dense_update(cfg, batch)

    topo = make_topology(cfg.mesh)
    model = get_model(cfg.model)
    specs = state_partition_specs(model, cfg, topo)
    state = topo.device_put_state(init_train_state(model, cfg, topo), specs)
    step_fn = build_train_step(model, cfg, topo, constant(LR))
    state, metrics = step_fn(state, topo.device_put_batch(batch))

    np.testing.assert_allclose(float(metrics["loss"]), float(want_loss),
                               **LOSS_TOL)  # 2e-4 under the check_rep shim
    got = jax.device_get(state.params)
    want_stacked = transformer.stack_block_params(want_params)
    assert_update_parity(got, want_stacked)


@pytest.mark.parametrize("n_replicas,n_stage,n_seq,microbatches", [
    (2, 2, 2, 2),   # DP × PP × SP (ring attention inside the pipeline)
    (1, 2, 4, 2),   # PP × wide SP
])
def test_pp_sp_step_matches_dense_update(n_replicas, n_stage, n_seq,
                                         microbatches):
    """PP×SP: the seq axis shards tokens through the pipeline stages
    (ring attention collectives run lockstep inside the pipeline scan)
    and the partial SP loss psums back to the dense loss exactly."""
    cfg = _cfg(n_replicas=n_replicas)
    cfg = cfg.override({"mesh.num_replicas": n_replicas,
                        "mesh.pipeline_parallelism": n_stage,
                        "mesh.seq_parallelism": n_seq,
                        "mesh.pipeline_microbatches": microbatches})
    batch = _tokens(cfg)
    want_loss, want_params = _dense_update(cfg, batch)

    topo = make_topology(cfg.mesh)
    model = get_model(cfg.model)
    specs = state_partition_specs(model, cfg, topo)
    state = topo.device_put_state(init_train_state(model, cfg, topo), specs)
    step_fn = build_train_step(model, cfg, topo, constant(LR))
    state, metrics = step_fn(state, topo.device_put_batch(batch,
                                                          seq_sharded=True))

    np.testing.assert_allclose(float(metrics["loss"]), float(want_loss),
                               **LOSS_TOL)  # 2e-4 under the check_rep shim
    got = jax.device_get(state.params)
    want_stacked = transformer.stack_block_params(want_params)
    assert_update_parity(got, want_stacked)


def test_trainer_end_to_end_dp_pp(tmp_train_dir):
    """Full Trainer on (replica=2, stage=4): quorum on the replica
    axis, async checkpointing, resume with stacked params."""
    from distributedmnist_tpu.train.loop import Trainer

    cfg = _cfg(n_replicas=2)
    cfg = cfg.override({
        "mesh.num_replicas": 2, "mesh.pipeline_parallelism": 4,
        "mesh.pipeline_microbatches": 2,
        "sync.mode": "quorum", "sync.num_replicas_to_aggregate": 1,
        "sync.straggler_profile": "lognormal",
        "train.max_steps": 12, "train.train_dir": tmp_train_dir,
        "train.log_every_steps": 6, "train.save_interval_secs": 0,
        "train.save_interval_steps": 6,
    })
    tr = Trainer(cfg)
    summary = tr.run()
    assert summary["final_step"] == 12
    assert summary["last_metrics"]["num_contributors"] == 1.0
    ev = tr.evaluate("test")
    assert np.isfinite(ev["loss"])

    tr2 = Trainer(cfg.override({"train.resume": True, "train.max_steps": 14}))
    assert tr2._start_step == 12
    assert tr2.run()["final_step"] == 14


# ---------------------------------------------------------------------------
# Interleaved 1F1B schedule
# ---------------------------------------------------------------------------

def test_1f1b_schedule_valid_and_fewer_idle_ticks():
    """The measured bubble comparison: at M ≥ 2S with v ≥ 2 virtual
    chunks, the fused 1F1B schedule must have FEWER idle chunk-slots
    than GPipe's 2·S·(S−1)·v (GPipe's 2(S−1) stage-work bubble, spread
    over v chunk-works per stage-work)."""
    from distributedmnist_tpu.ops.pipeline import make_1f1b_schedule

    for S, v, M in [(2, 2, 4), (2, 2, 8), (4, 2, 8), (4, 2, 16),
                    (2, 3, 12)]:
        tbl = make_1f1b_schedule(S, v, M)
        gpipe_idle = 2 * S * (S - 1) * v
        assert tbl["idle_slots"] < gpipe_idle, (S, v, M, tbl["idle_slots"])
        # wall comparison in chunk-works: T single-work ticks vs
        # GPipe's 2(M+S-1) stage-ticks of v chunk-works each
        assert tbl["ticks"] < 2 * (M + S - 1) * v, (S, v, M)
        # validity: every (mb, chunk) forwarded + backwarded exactly once
        kind, slot, mb = tbl["kind"], tbl["slot"], tbl["mb"]
        f_seen, b_seen = set(), set()
        for t in range(tbl["ticks"]):
            for d in range(S):
                c = slot[t, d] * S + d
                if kind[t, d] in (1, 2):
                    f_seen.add((mb[t, d], c))
                elif kind[t, d] == 3:
                    assert (mb[t, d], c) in f_seen  # B after own F
                    b_seen.add((mb[t, d], c))
        assert len(f_seen) == len(b_seen) == M * S * v
    # v=1 (non-interleaved): no worse than GPipe
    tbl = make_1f1b_schedule(4, 1, 8)
    assert tbl["idle_slots"] <= 2 * 4 * 3 * 1


@pytest.mark.parametrize("n_replicas,n_stage,chunks,microbatches,layers", [
    (1, 2, 2, 4, 4),    # S=2, v=2: the canonical interleaved shape
    (2, 2, 2, 2, 4),    # DP × interleaved 1F1B
    (1, 4, 1, 4, 4),    # v=1: plain (non-interleaved) 1F1B
])
def test_1f1b_step_matches_dense_update(n_replicas, n_stage, chunks,
                                        microbatches, layers):
    """Gold parity: the fused-schedule training step — explicit
    recompute-vjp backward, interleaved chunk placement, banked
    embedding cotangents, tied-head gradient assembly — must reproduce
    the dense single-device update exactly (same bar as the GPipe
    tests above)."""
    cfg = _cfg(n_replicas=n_replicas, layers=layers)
    cfg = cfg.override({"mesh.num_replicas": n_replicas,
                        "mesh.pipeline_parallelism": n_stage,
                        "mesh.pipeline_microbatches": microbatches,
                        "mesh.pipeline_schedule": "1f1b",
                        "mesh.pipeline_chunks": chunks})
    batch = _tokens(cfg)
    want_loss, want_params = _dense_update(cfg, batch)

    topo = make_topology(cfg.mesh)
    model = get_model(cfg.model)
    specs = state_partition_specs(model, cfg, topo)
    state = topo.device_put_state(init_train_state(model, cfg, topo), specs)
    step_fn = build_train_step(model, cfg, topo, constant(LR))
    state, metrics = step_fn(state, topo.device_put_batch(batch))

    np.testing.assert_allclose(float(metrics["loss"]), float(want_loss),
                               **LOSS_TOL)  # 2e-4 under the check_rep shim
    assert 0.0 <= float(metrics["train_acc"]) <= 1.0
    got = jax.device_get(state.params)
    want_stacked = transformer.stack_block_params_chunked(
        want_params, n_stage, chunks)
    assert_update_parity(got, want_stacked)


def test_resume_refuses_cross_schedule_layout(tmp_train_dir):
    """A gpipe checkpoint must not restore into a 1f1b run: the two
    stacked layouts shape-match but order layers differently, so a
    silent restore would permute the model."""
    from distributedmnist_tpu.train.loop import Trainer

    base = _cfg(n_replicas=2).override({
        "mesh.num_replicas": 2, "mesh.pipeline_parallelism": 2,
        "mesh.pipeline_microbatches": 2,
        "train.max_steps": 2, "train.train_dir": tmp_train_dir,
        "train.log_every_steps": 2, "train.save_interval_secs": 0,
        "train.save_interval_steps": 2,
    })
    Trainer(base).run()
    with pytest.raises(ValueError, match="pipeline layout"):
        Trainer(base.override({"mesh.pipeline_schedule": "1f1b",
                               "mesh.pipeline_chunks": 2,
                               "train.max_steps": 4}))


@pytest.mark.parametrize("n_replicas,n_stage,n_model,chunks,microbatches", [
    (2, 2, 2, 2, 2),    # DP × 1F1B × TP
    (1, 2, 4, 2, 4),    # 1F1B × wide TP
])
def test_1f1b_tp_step_matches_dense_update(n_replicas, n_stage, n_model,
                                           chunks, microbatches):
    """Gold parity for 1F1B × tensor parallelism: the Megatron
    row-parallel psums (and the AD-inserted psums for TP-replicated
    leaves) execute inside the engine's stage-varying switch branches —
    legal because every model-axis peer group shares one stage
    coordinate and so takes the same branch each tick."""
    cfg = _cfg(n_replicas=n_replicas)
    cfg = cfg.override({"mesh.num_replicas": n_replicas,
                        "mesh.pipeline_parallelism": n_stage,
                        "mesh.model_parallelism": n_model,
                        "mesh.pipeline_microbatches": microbatches,
                        "mesh.pipeline_schedule": "1f1b",
                        "mesh.pipeline_chunks": chunks})
    batch = _tokens(cfg)
    want_loss, want_params = _dense_update(cfg, batch)

    topo = make_topology(cfg.mesh)
    model = get_model(cfg.model)
    specs = state_partition_specs(model, cfg, topo)
    state = topo.device_put_state(init_train_state(model, cfg, topo), specs)
    step_fn = build_train_step(model, cfg, topo, constant(LR))
    state, metrics = step_fn(state, topo.device_put_batch(batch))

    np.testing.assert_allclose(float(metrics["loss"]), float(want_loss),
                               **LOSS_TOL)  # 2e-4 under the check_rep shim
    got = jax.device_get(state.params)
    want_stacked = transformer.stack_block_params_chunked(
        want_params, n_stage, chunks)
    assert_update_parity(got, want_stacked)


@pytest.mark.parametrize("n_replicas,n_stage,n_seq,chunks,microbatches", [
    (2, 2, 2, 2, 2),    # DP × 1F1B × SP (Ulysses attention in the chunks)
    (1, 2, 4, 2, 4),    # 1F1B × wide SP
])
def test_1f1b_sp_step_matches_dense_update(n_replicas, n_stage, n_seq,
                                           chunks, microbatches):
    """Gold parity for 1F1B × sequence parallelism: Ulysses all-to-alls
    run inside the switch branches (group-local rendezvous over seq
    peers that share the stage coordinate — ring's global-rendezvous
    ppermute cannot, see the refusal test), the seed branch computes
    the cross-shard partial loss against targets shifted OUTSIDE the
    engine, and the outer psum over the seq axis reassembles the dense
    update exactly."""
    cfg = _cfg(n_replicas=n_replicas)
    cfg = cfg.override({"model.sp_attention": "ulysses",
                        "mesh.num_replicas": n_replicas,
                        "mesh.pipeline_parallelism": n_stage,
                        "mesh.seq_parallelism": n_seq,
                        "mesh.pipeline_microbatches": microbatches,
                        "mesh.pipeline_schedule": "1f1b",
                        "mesh.pipeline_chunks": chunks})
    batch = _tokens(cfg)
    want_loss, want_params = _dense_update(cfg, batch)

    topo = make_topology(cfg.mesh)
    model = get_model(cfg.model)
    specs = state_partition_specs(model, cfg, topo)
    state = topo.device_put_state(init_train_state(model, cfg, topo), specs)
    step_fn = build_train_step(model, cfg, topo, constant(LR))
    state, metrics = step_fn(state, topo.device_put_batch(batch,
                                                          seq_sharded=True))

    np.testing.assert_allclose(float(metrics["loss"]), float(want_loss),
                               **LOSS_TOL)  # 2e-4 under the check_rep shim
    got = jax.device_get(state.params)
    want_stacked = transformer.stack_block_params_chunked(
        want_params, n_stage, chunks)
    assert_update_parity(got, want_stacked)


def test_1f1b_sp_refuses_ring_attention():
    """Ring attention's ppermute rendezvouses globally — inside the
    fused engine's stage-varying branches it would deadlock, so the
    registry refuses the combination up front (Ulysses composes)."""
    cfg = _cfg().override({"model.sp_attention": "ring",
                           "mesh.num_replicas": 1,
                           "mesh.pipeline_parallelism": 2,
                           "mesh.seq_parallelism": 2,
                           "mesh.pipeline_microbatches": 2,
                           "mesh.pipeline_schedule": "1f1b",
                           "mesh.pipeline_chunks": 2})
    with pytest.raises(ValueError, match="ulysses"):
        build_train_step(get_model(cfg.model), cfg, make_topology(cfg.mesh),
                         constant(LR))


def test_trainer_end_to_end_1f1b(tmp_train_dir):
    """Full Trainer on (replica=2, stage=2, model=2): training,
    checkpoint/resume with the chunk-interleaved TP-sharded layout, and
    eval through the chunked-ring forward with Megatron shards."""
    from distributedmnist_tpu.train.loop import Trainer

    cfg = _cfg(n_replicas=2)
    cfg = cfg.override({
        "mesh.num_replicas": 2, "mesh.pipeline_parallelism": 2,
        "mesh.model_parallelism": 2, "mesh.pipeline_microbatches": 2,
        "mesh.pipeline_schedule": "1f1b", "mesh.pipeline_chunks": 2,
        "train.max_steps": 10, "train.train_dir": tmp_train_dir,
        "train.log_every_steps": 5, "train.save_interval_secs": 0,
        "train.save_interval_steps": 5,
    })
    tr = Trainer(cfg)
    summary = tr.run()
    assert summary["final_step"] == 10
    ev = tr.evaluate("test")
    assert np.isfinite(ev["loss"])

    tr2 = Trainer(cfg.override({"train.resume": True, "train.max_steps": 12}))
    assert tr2._start_step == 10
    assert tr2.run()["final_step"] == 12
