#!/usr/bin/env python
"""Benchmark entry point — prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Measures steady-state training throughput (images/sec) of the flagship
MNIST CNN under sync-replica SGD semantics on whatever devices are
visible (one TPU chip under the driver; the virtual CPU mesh works too).

The reference publishes no numbers (README.md:1 is bare — SURVEY §6),
so vs_baseline is reported against the north-star-derived nominal in
BASELINE.json when present, else 1.0.
"""

import json
import sys
import time

import jax
import numpy as np


def main() -> None:
    from distributedmnist_tpu.core.config import ExperimentConfig
    from distributedmnist_tpu.core.mesh import make_topology
    from distributedmnist_tpu.data.datasets import make_synthetic
    from distributedmnist_tpu.models.registry import get_model
    from distributedmnist_tpu.parallel.api import build_train_step, init_train_state
    from distributedmnist_tpu.train.lr_schedule import constant

    n_dev = len(jax.devices())
    batch = 4096 * max(1, n_dev)
    cfg = ExperimentConfig.from_dict({
        "data": {"dataset": "synthetic", "batch_size": batch},
        "model": {"compute_dtype": "bfloat16"},
        "sync": {"mode": "sync"},
    })
    topo = make_topology()
    model = get_model(cfg.model)
    state = topo.device_put_replicated(init_train_state(model, cfg))
    step_fn = build_train_step(model, cfg, topo, constant(8e-4))

    ds = make_synthetic(num_train=batch, num_test=256)
    host_batch = {"image": ds.train.images[:batch], "label": ds.train.labels[:batch]}
    gbatch = topo.device_put_batch(host_batch)

    # Sync by FETCHING a scalar, not block_until_ready: on the tunneled
    # TPU platform block_until_ready can return before the enqueued
    # programs drain, which once inflated this number ~100x. A host
    # transfer of an output scalar is an unambiguous queue drain.
    warmup, timed = 10, 100
    for _ in range(warmup):
        state, metrics = step_fn(state, gbatch)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(timed):
        state, metrics = step_fn(state, gbatch)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    images_per_sec = timed * batch / dt
    per_chip = images_per_sec / n_dev

    baseline = None
    try:
        with open("BASELINE.json") as f:
            baseline = json.load(f).get("published", {}).get("images_per_sec_per_chip")
    except (OSError, json.JSONDecodeError):
        pass
    vs = per_chip / baseline if baseline else 1.0

    print(json.dumps({
        "metric": "mnist_cnn_sync_sgd_images_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(vs, 3),
    }))
    # extra context on stderr (never pollutes the JSON line)
    print(f"# devices={n_dev} global_batch={batch} steps={timed} "
          f"wall={dt:.3f}s total={images_per_sec:.0f} img/s", file=sys.stderr)


if __name__ == "__main__":
    main()
