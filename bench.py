#!/usr/bin/env python
"""Benchmark entry point — prints ONE self-contained JSON line on
stdout, LAST:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
   "cases": [<every additional case record>]}

The top-level metric is the headline: steady-state training throughput
(images/sec) of the flagship MNIST CNN under sync-replica SGD
semantics on whatever devices are visible (one TPU chip under the
driver; the virtual CPU mesh works too). ``vs_baseline`` ratchets
against the round-1 number recorded in BASELINE.json.published — a
regression shows up as < 1.0, not as a silent 1.0.

``cases`` carries the rest, so the artifact is verifiable from the one
stdout line alone (VERDICT weak #2: the old layout printed the
headline first and cases on stderr, and the driver's last-bytes
capture lost the headline entirely):
  * transformer+flash-attention train step, model TFLOP/s
  * quorum / cdf aggregation-discipline overhead vs plain sync,
    median-gated over interleaved repeats (SURVEY §7: timing capture
    must not cost scaling efficiency)
  * native C++ prefetch loader vs the pure-python batch pipeline

Per-case records still stream to stderr as they complete (progress for
a human following the run); stdout is reserved for the final artifact.

The reference publishes no numbers (README.md:1 is bare — SURVEY §6);
the baseline is this repo's own round-1 measurement.
"""

import json
import statistics
import sys
import time

import jax
import numpy as np
from jax import lax


def _drain(metrics) -> None:
    # Sync by FETCHING a scalar, not block_until_ready: on the tunneled
    # TPU platform block_until_ready can return before the enqueued
    # programs drain, which once inflated throughput ~100x. A host
    # transfer of an output scalar is an unambiguous queue drain.
    float(jax.tree.leaves(metrics)[0])


def _case(record: dict) -> None:
    print(json.dumps(record), file=sys.stderr)


def _published(key: str):
    """A ratchet anchor from BASELINE.json.published — anchored to this
    file, not the cwd (a cwd-relative read would silently turn the
    ratchet back into a constant 1.0)."""
    try:
        from pathlib import Path
        with open(Path(__file__).parent / "BASELINE.json") as f:
            return json.load(f).get("published", {}).get(key)
    except (OSError, json.JSONDecodeError):
        return None


def _vs(value: float, anchor, what: str):
    """Ratchet ratio, or None (plus a loud stderr note) when the anchor
    is missing — a corrupted BASELINE.json must not silently turn the
    ratchet back into a constant 1.0."""
    if not anchor:
        print(f"# WARNING: no published anchor for {what}; "
              "vs_baseline unavailable", file=sys.stderr)
        return None
    return round(value / anchor, 3)


def _env_stamp() -> dict:
    """Where this artifact was actually measured. Round 3's official
    capture ran ~18x below the in-session numbers and the artifact
    could not say whether the backend, the tunnel, or contention was at
    fault — every record now carries the platform identity."""
    d = jax.devices()[0]
    return {"platform": d.platform, "device_kind": d.device_kind,
            "num_devices": len(jax.devices()),
            "jax_version": jax.__version__}


class _ChunkTimer:
    """Persistent jitted runner for an ON-DEVICE ``lax.scan`` of
    ``chunk_len`` training steps: compile + warm ONCE, then
    :meth:`measure` any number of times.

    The round-3 driver capture showed per-step wall times ~18x the
    in-session steady state; with one host dispatch per step, the
    artifact could not separate device throughput from host/tunnel
    pathology. Scanning the step on-device makes the timed region one
    XLA program per chunk: whatever the relay latency is, it amortizes
    over ``chunk_len`` steps, and the per-chunk spread (reported as a
    histogram) shows contention instead of hiding it. ≙ the steady-
    state throughput the reference reports from in-run step timing
    (src/distributed_train.py:365-371).

    Persistence is what makes interleaved-repeat gates affordable
    (VERDICT weak #1): re-measuring a mode costs only its timed chunks,
    not a recompile, so sync/quorum/cdf can alternate on the same chip
    and drift lands on every mode equally.
    """

    def __init__(self, step_fn, state, gbatch, chunk_len: int):
        def chunk(st, batch):
            def body(carry, _):
                new_state, metrics = step_fn(carry, batch)
                return new_state, metrics["loss"]
            final, losses = lax.scan(body, st, None, length=chunk_len)
            return final, losses[-1]

        self.chunk_len = chunk_len
        self._gbatch = gbatch
        self._run = jax.jit(chunk, donate_argnums=0)
        t0 = time.perf_counter()
        state, loss = self._run(state, gbatch)
        float(loss)  # drain (see _drain)
        self.compile_s = time.perf_counter() - t0
        # One untimed warm chunk: the first post-compile dispatch pays
        # a host/tunnel ramp (measured 4-14 ms/step of pure jitter at
        # the flash shape — two runs of identical code differed only
        # there). Steady-state device throughput is the quantity every
        # case reports; the warm chunk is excluded from the timed
        # window uniformly, and per_step_ms_by_chunk shows the spread.
        state, loss = self._run(state, gbatch)
        float(loss)
        self.state = state

    def measure(self, n_chunks: int) -> list[float]:
        """Per-chunk wall seconds for ``n_chunks`` timed chunks.

        Dispatch every chunk before fetching any: the device queue runs
        the chunks back-to-back while the ~70 ms tunnel relay of each
        fetch overlaps the next chunk's compute, so exactly ONE relay
        latency lands in the timed window instead of one per chunk.
        """
        losses = []
        t0 = time.perf_counter()
        for _ in range(n_chunks):
            self.state, loss = self._run(self.state, self._gbatch)
            losses.append(loss)
        times, prev = [], t0
        for loss in losses:
            float(loss)  # returns when that chunk has drained
            now = time.perf_counter()
            times.append(now - prev)
            prev = now
        return times


def _scan_chunks(step_fn, state, gbatch, chunk_len: int, n_chunks: int):
    """One-shot compile → warm → time ``n_chunks`` chunks.

    Returns (chunk_seconds list, compile_seconds, final_state).
    """
    timer = _ChunkTimer(step_fn, state, gbatch, chunk_len)
    times = timer.measure(n_chunks)
    return times, timer.compile_s, timer.state


def _build(cfg_dict: dict, topo=None):
    from distributedmnist_tpu.core.config import (ExperimentConfig,
                                                  effective_model_config)
    from distributedmnist_tpu.core.mesh import make_topology
    from distributedmnist_tpu.models.registry import get_model
    from distributedmnist_tpu.parallel.api import (build_train_step,
                                                   init_train_state)
    from distributedmnist_tpu.train.lr_schedule import (
        constant, warmup_polynomial_decay)

    cfg = ExperimentConfig.from_dict(cfg_dict)
    topo = topo or make_topology()
    # same resolutions the Trainer applies: precision.compute_dtype
    # through the shared helper, and the configured schedule — a case
    # whose recipe names warmup/poly must actually MEASURE it
    model = get_model(effective_model_config(cfg))
    if cfg.optim.schedule == "polynomial":
        schedule = warmup_polynomial_decay(
            cfg.optim.initial_learning_rate, cfg.optim.warmup_steps,
            cfg.optim.decay_total_steps or cfg.train.max_steps,
            cfg.optim.end_learning_rate, cfg.optim.poly_power)
    else:
        schedule = constant(8e-4)  # throughput cases: fixed, decay-free
    state = topo.device_put_replicated(init_train_state(model, cfg))
    step_fn = build_train_step(model, cfg, topo, schedule)
    return cfg, topo, model, state, step_fn


def bench_cnn_sync() -> dict:
    """Headline: flagship CNN, plain sync mode. The timed region is an
    on-device scan (one dispatch per chunk of steps) so the number is
    device throughput, not host/tunnel round-trip pacing."""
    from distributedmnist_tpu.data.datasets import make_synthetic

    n_dev = len(jax.devices())
    batch = 4096 * max(1, n_dev)
    cfg, topo, model, state, step_fn = _build({
        "data": {"dataset": "synthetic", "batch_size": batch},
        "model": {"compute_dtype": "bfloat16"},
        "sync": {"mode": "sync"},
    })
    ds = make_synthetic(num_train=batch, num_test=256)
    gbatch = topo.device_put_batch(
        {"image": ds.train.images[:batch], "label": ds.train.labels[:batch]})
    chunk_len, n_chunks = 50, 6
    times, compile_s, _ = _scan_chunks(step_fn, state, gbatch,
                                       chunk_len, n_chunks)
    dt = sum(times)
    timed = chunk_len * n_chunks
    images_per_sec = timed * batch / dt
    per_chip = images_per_sec / n_dev
    step_ms = [round(t / chunk_len * 1e3, 3) for t in times]

    vs = _vs(per_chip, _published("images_per_sec_per_chip"),
             "images_per_sec_per_chip")
    print(f"# devices={n_dev} global_batch={batch} steps={timed} "
          f"wall={dt:.3f}s total={images_per_sec:.0f} img/s "
          f"compile={compile_s:.1f}s", file=sys.stderr)
    record = {
        "metric": "mnist_cnn_sync_sgd_images_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": vs,
        "detail": {**_env_stamp(), "compile_s": round(compile_s, 2),
                   "chunk_len": chunk_len,
                   "per_step_ms_by_chunk": step_ms},
    }
    if vs is not None and vs < 0.5:
        record["degraded"] = True  # loud: the chip ran far below the
        # committed ratchet — see detail for platform/contention evidence
    return record


def bench_transformer_flash() -> dict:
    """Transformer with the Pallas flash-attention kernels (fwd+bwd):
    model TFLOP/s per chip — the committed artifact for the kernel
    path's performance claims."""
    n_dev = len(jax.devices())
    d, L, H, S, V = 2048, 4, 16, 1024, 1024
    B = 16 * max(1, n_dev)
    cfg, topo, model, state, step_fn = _build({
        "data": {"dataset": "synthetic_lm", "batch_size": B},
        "model": {"name": "transformer", "model_dim": d, "num_layers": L,
                  "num_heads": H, "seq_len": S, "vocab_size": V,
                  "attention_impl": "flash", "compute_dtype": "bfloat16"},
        "sync": {"mode": "sync"},
    })
    rng = np.random.default_rng(0)
    toks = rng.integers(0, V, (B, S), dtype=np.int32)
    gbatch = topo.device_put_batch({"image": toks, "label": toks.copy()})
    # 50 timed steps: the one tunnel-relay latency that necessarily
    # lands in the timed window (~13 ms here) must stay <0.5% of it
    chunk_len, n_chunks = 10, 5
    times, compile_s, _ = _scan_chunks(step_fn, state, gbatch,
                                       chunk_len, n_chunks)
    dt = sum(times)
    timed = chunk_len * n_chunks

    # Matmul FLOPs per token, fwd: qkv 6d² + out-proj 2d² + MLP 16d²
    # per layer, plus causal attention 2·(2·S·d)·½ per layer, plus the
    # tied head 2dV. Train step ≈ 3× fwd (bwd ≈ 2× fwd).
    fwd_per_token = L * (24 * d * d + 2 * S * d) + 2 * d * V
    flops = 3 * fwd_per_token * B * S * timed
    tflops = flops / dt / 1e12 / n_dev
    vs = _vs(tflops, _published("transformer_flash_tflops_per_chip"),
             "transformer_flash_tflops_per_chip")
    record = {"metric": "transformer_flash_train_tflops_per_chip",
              "value": round(tflops, 2), "unit": "TFLOP/s/chip",
              "vs_baseline": vs,
              "detail": {"dims": {"d": d, "L": L, "H": H, "S": S, "V": V,
                                  "B": B},
                         "steps_per_sec": round(timed / dt, 3),
                         "tokens_per_sec": round(timed * B * S / dt, 1),
                         "compile_s": round(compile_s, 2),
                         "per_step_ms_by_chunk": [
                             round(t / chunk_len * 1e3, 2) for t in times],
                         **_env_stamp()}}
    if vs is not None and vs < 0.5:
        record["degraded"] = True
    return record


def bench_flash_long_context() -> dict:
    """Long-context case: flash attention at S=8192 on one chip, where
    the attention term (2·S·d per token per layer) rivals the matmul
    FLOPs — the regime ring/Ulysses SP extends across chips. Exercises
    the Pallas kernels' tiling at depth (fwd + bwd), with remat on —
    the long-sequence HBM recipe the framework ships."""
    n_dev = len(jax.devices())
    d, L, H, S, V = 1024, 2, 8, 8192, 1024
    B = 2 * max(1, n_dev)
    cfg, topo, model, state, step_fn = _build({
        "data": {"dataset": "synthetic_lm", "batch_size": B},
        "model": {"name": "transformer", "model_dim": d, "num_layers": L,
                  "num_heads": H, "seq_len": S, "vocab_size": V,
                  "attention_impl": "flash", "remat": True,
                  "compute_dtype": "bfloat16"},
        "sync": {"mode": "sync"},
    })
    rng = np.random.default_rng(0)
    toks = rng.integers(0, V, (B, S), dtype=np.int32)
    gbatch = topo.device_put_batch({"image": toks, "label": toks.copy()})
    chunk_len, n_chunks = 8, 4
    times, compile_s, _ = _scan_chunks(step_fn, state, gbatch,
                                       chunk_len, n_chunks)
    dt = sum(times)
    timed = chunk_len * n_chunks

    # the shipped selective-remat policy (save_attn: attention
    # residuals stay resident, only norms/projections/MLP recompute) —
    # measured at the same shape so the artifact records the policy's
    # win without changing the anchor metric's full-remat definition
    cfg2, topo2, model2, state2, step_fn2 = _build({
        "data": {"dataset": "synthetic_lm", "batch_size": B},
        "model": {"name": "transformer", "model_dim": d, "num_layers": L,
                  "num_heads": H, "seq_len": S, "vocab_size": V,
                  "attention_impl": "flash", "remat": True,
                  "remat_policy": "save_attn",
                  "compute_dtype": "bfloat16"},
        "sync": {"mode": "sync"},
    }, topo)
    gbatch2 = topo2.device_put_batch({"image": toks, "label": toks.copy()})
    times2, _, _ = _scan_chunks(step_fn2, state2, gbatch2, chunk_len, 3)
    tok_full = timed * B * S / dt
    tok_sa = chunk_len * 3 * B * S / sum(times2)

    fwd_per_token = L * (24 * d * d + 2 * S * d) + 2 * d * V
    # remat recomputes each block's forward in the backward: ≈4× fwd
    # of model FLOPs per train step instead of 3× — report the
    # EXECUTED rate (hardware utilization), with the algorithmic 3×
    # rate alongside
    flops_exec = 4 * fwd_per_token * B * S * timed
    tflops = flops_exec / dt / 1e12 / n_dev
    vs = _vs(tflops, _published("flash_long_context_tflops_per_chip"),
             "flash_long_context_tflops_per_chip")
    record = {"metric": "flash_long_context_train_tflops_per_chip",
              "value": round(tflops, 2),
              "unit": "TFLOP/s/chip",
              "vs_baseline": vs,
              "detail": {
                  "dims": {"d": d, "L": L, "H": H, "S": S, "V": V, "B": B},
                  "attention_fraction": round(
                      2 * S / (24 * d + 2 * S + 2 * V / L), 3),
                  "model_tflops_per_chip": round(
                      3 * fwd_per_token * B * S * timed / dt / 1e12
                      / n_dev, 2),
                  "tokens_per_sec": round(tok_full, 1),
                  "save_attn_policy": {
                      "tokens_per_sec": round(tok_sa, 1),
                      "speedup_vs_full_remat": round(tok_sa / tok_full, 3)},
                  "compile_s": round(compile_s, 2),
                  **_env_stamp()}}
    if vs is not None and vs < 0.5:
        record["degraded"] = True
    return record


def bench_mode_overhead() -> list[dict]:
    """Aggregation-discipline tax: quorum and cdf modes vs plain sync
    on the same model/batch. The masks, timing model, rank reduction
    and [n]-vector gathers must stay within a 10% throughput budget
    (SURVEY §7 'timing capture must not cost scaling efficiency').

    The gate is the MEDIAN over ≥3 INTERLEAVED repeats — one
    sync/quorum/cdf rotation per repeat, so shared-chip drift hits all
    modes alike and a single noisy window cannot flip the verdict
    (VERDICT weak #1: round 5's 11.82% "failure" re-measured at -1.84%
    the same day; history 0.14 → 2.95 → 11.82 → -1.84%). All repeats
    land in the artifact. ≙ the stats discipline the reference applies
    to worker step times, tools/benchmark.py:86-111, applied to the
    harness itself.
    """
    from distributedmnist_tpu.data.datasets import make_synthetic

    n_dev = len(jax.devices())
    batch = 1024 * max(1, n_dev)
    ds = make_synthetic(num_train=batch, num_test=256)
    host_batch = {"image": ds.train.images[:batch],
                  "label": ds.train.labels[:batch]}

    k = max(1, n_dev - 1)
    modes = {
        "sync": {"mode": "sync"},
        "quorum": {"mode": "quorum", "num_replicas_to_aggregate": k,
                   "straggler_profile": "lognormal"},
        "cdf": {"mode": "cdf"},
    }
    chunk_len, n_chunks, n_repeats = 20, 2, 3

    timers: dict[str, _ChunkTimer] = {}
    programs: dict[str, dict] = {}
    for name, sync_cfg in modes.items():
        cfg, topo, model, state, step_fn = _build({
            "data": {"dataset": "synthetic", "batch_size": batch},
            "model": {"compute_dtype": "bfloat16"},
            "sync": sync_cfg,
        })
        gbatch = topo.device_put_batch(host_batch)
        try:
            # structural evidence BEFORE the timer donates the state:
            # the lowered per-step program, hashed. The per-worker CDF
            # instrumentation (the [n] step-time vector + contribution
            # flags) is emitted in EVERY mode including sync, and cdf's
            # full-barrier flag is the same constant as sync's — so the
            # cdf program is byte-identical StableHLO to sync's, and
            # any measured "cdf overhead" is capture noise by
            # construction (the r05 11.82% reading; gated since by the
            # interleaved-repeat median below).
            import hashlib
            txt = step_fn.jitted.lower(
                state, gbatch, topo.zeros_measured(),
                step_fn.default_discipline()).as_text()
            programs[name] = {
                "stablehlo_lines": txt.count("\n"),
                "stablehlo_sha256": hashlib.sha256(
                    txt.encode()).hexdigest()[:16]}
        except Exception as e:  # evidence is best-effort, never fatal
            programs[name] = {"error": f"{type(e).__name__}: {e}"}
        timers[name] = _ChunkTimer(step_fn, state, gbatch, chunk_len)

    rates: dict[str, list[float]] = {name: [] for name in modes}
    for _ in range(n_repeats):
        for name, timer in timers.items():  # one rotation per repeat
            dt = sum(timer.measure(n_chunks))
            rates[name].append(chunk_len * n_chunks * batch / dt)

    med = {name: statistics.median(r) for name, r in rates.items()}
    records = []
    for mode in ("quorum", "cdf"):
        by_repeat = [round((s - m) / s * 100, 2)
                     for s, m in zip(rates["sync"], rates[mode])]
        overhead = (med["sync"] - med[mode]) / med["sync"]
        same_program = (programs.get(mode) == programs.get("sync")
                        and "error" not in programs.get(mode, {"error": 1}))
        records.append({
            "metric": f"{mode}_mode_overhead_vs_sync",
            "value": round(overhead * 100, 2), "unit": "percent",
            "within_10pct_budget": bool(overhead < 0.10),
            "detail": {
                "gate": f"median of {n_repeats} interleaved repeats",
                "overhead_pct_by_repeat": by_repeat,
                "sync_img_per_sec_median": round(med["sync"], 1),
                f"{mode}_img_per_sec_median": round(med[mode], 1),
                # compiled-program identity: when this mode's lowered
                # StableHLO hashes equal to sync's, the instrumentation
                # adds literally zero ops and nonzero "overhead"
                # readings are wall-clock capture noise
                "program": programs.get(mode),
                "program_identical_to_sync": same_program,
                "img_per_sec_by_repeat": {
                    "sync": [round(r, 1) for r in rates["sync"]],
                    mode: [round(r, 1) for r in rates[mode]]}}})
    return records


def bench_native_loader() -> dict:
    """Native C++ data path vs pure python, measured at its two real
    jobs: (a) cold idx decode throughput (gunzip + parse — what the C++
    decoder exists for), and (b) steady-state pipeline rate with an
    overlapping consumer (~2 ms of work per batch, the realistic shape:
    prefetch hides batch prep behind device compute; a zero-work drain
    loop would only measure thread handoff against itself)."""
    import tempfile
    from pathlib import Path

    from distributedmnist_tpu.core.config import DataConfig
    from distributedmnist_tpu.data import datasets as dsm
    from distributedmnist_tpu.data.datasets import make_synthetic
    from distributedmnist_tpu.data.pipeline import make_train_iterator

    # (a) decode throughput on an archive-sized idx.gz (60k×28×28)
    ds = make_synthetic(num_train=60000, num_test=256)
    u8 = np.clip(np.round((ds.train.images[..., 0] + 0.5) * 255),
                 0, 255).astype(np.uint8)
    with tempfile.TemporaryDirectory() as td:
        path = Path(td) / "train-images-idx3-ubyte.gz"
        dsm.write_idx_ubyte(path, u8)
        nbytes = u8.nbytes
        decode = {}
        try:
            from distributedmnist_tpu.data.native_loader import read_idx
            t0 = time.perf_counter()
            read_idx(path)
            decode["native_MBps"] = round(nbytes / (time.perf_counter() - t0)
                                          / 1e6, 1)
        except ImportError:
            decode["native_MBps"] = None
        import gzip as _gz
        import struct as _st
        t0 = time.perf_counter()
        with _gz.open(path, "rb") as f:  # the pure-python fallback path
            magic = _st.unpack(">HBB", f.read(4))
            dims = _st.unpack(f">{magic[2]}I", f.read(4 * magic[2]))
            np.frombuffer(f.read(int(np.prod(dims))),
                          dtype=np.uint8).reshape(dims)
        decode["python_MBps"] = round(nbytes / (time.perf_counter() - t0)
                                      / 1e6, 1)

    # (b) pipeline rate under TWO consumer shapes. Construct both
    # iterators DIRECTLY — make_train_iterator's gate would silently
    # hand back the python pipeline for "native" and this case would
    # benchmark python against itself.
    #
    #   * cpu_busy: ≈2 ms of numpy per batch — models CPU-mesh
    #     training, where the consumer's compute owns the host core and
    #     a prefetch thread just fights it for cycles (the measured net
    #     slowdown behind make_train_iterator's CPU-backend gate).
    #   * device_blocked: the TRAIN LOOP's real shape on a TPU host —
    #     per batch a jitted dispatch (cheap), every log-cadence a
    #     scalar fetch that parks the host thread GIL-FREE in the
    #     PJRT/tunnel relay (~70 ms here). That parked window is where
    #     a 1-core host genuinely has spare cycles for the prefetch
    #     thread — the case that decides the production gate.
    import os

    from distributedmnist_tpu.data.pipeline import BatchIterator

    n_batches, batch, cadence = 120, 4096, 10
    work = np.zeros((256, 256), np.float32)
    dev_w = jax.device_put(np.zeros((128, 128), np.float32))
    dev_step = jax.jit(lambda a: (a @ a).sum())
    float(dev_step(dev_w))  # compile outside the timed region

    def consume_cpu_busy(i, pending):
        del i, pending
        work @ work

    def consume_device_blocked(i, pending):
        pending.append(dev_step(dev_w))   # async dispatch, host returns
        if (i + 1) % cadence == 0:
            float(pending[-1])            # GIL-free park in the relay
            pending.clear()

    # The native iterator is measured at TWO queue depths: the
    # PRODUCTION default (DataConfig.prefetch_batches = 2 — the depth
    # make_train_iterator's 1-core gate actually governs) and a deep
    # queue (=cadence). Round 4 benched only the deep queue and its
    # 1.07x contradicted the gate's depth-2 measurement; at matched
    # depth the gate and the bench agree (native ~0.90x on this
    # 1-core host — the gate correctly disables it).
    prod_depth = DataConfig().prefetch_batches
    variants = [("python", None), ("native", prod_depth),
                ("native_deep", cadence)]

    rates: dict = {}
    for shape, consume in (("cpu_busy", consume_cpu_busy),
                           ("device_blocked", consume_device_blocked)):
        for label, depth in variants:
            it = BatchIterator(ds.train, batch, seed=0)
            if depth is not None:
                try:
                    from distributedmnist_tpu.data.native_loader import (
                        NativePrefetcher)
                except ImportError as e:  # no C++ toolchain: still report
                    rates[f"{shape}_{label}"] = None
                    rates["native_error"] = f"{type(e).__name__}: {e}"
                    continue
                it = NativePrefetcher(it, depth=depth)
            next(it)  # spin-up cost out of the timed window
            pending: list = []
            t0 = time.perf_counter()
            for i in range(n_batches):
                next(it)
                consume(i, pending)
            rates[f"{shape}_{label}"] = n_batches / (time.perf_counter() - t0)
            if hasattr(it, "close"):
                it.close()

    def ratio(shape: str, label: str = "native"):
        n, p = rates.get(f"{shape}_{label}"), rates.get(f"{shape}_python")
        return round(n / p, 2) if n and p else rates.get("native_error")

    prod_ratio = ratio("device_blocked")
    native = rates.get("device_blocked_native")
    return ({"metric": "native_loader_overlapped_batches_per_sec",
           "value": round(native, 1) if native else None,
           "unit": "batches/sec",
           "detail": {"prefetch_depth_production": prod_depth,
                      "pipeline_speedup_vs_python": prod_ratio,
                      "pipeline_speedup_deep_queue": ratio(
                          "device_blocked", "native_deep"),
                      "cpu_busy_speedup_vs_python": ratio("cpu_busy"),
                      "gate_decision_matches_bench": (
                          None if not isinstance(prod_ratio, float)
                          else bool((prod_ratio < 1.0)
                                    == ((os.cpu_count() or 1) < 2))),
                      "rates_batches_per_sec": {
                          k: round(v, 1) for k, v in rates.items()
                          if isinstance(v, float)},
                      "batch": batch, "fetch_cadence": cadence,
                      "host_cpu_count": os.cpu_count(),
                      "backend": jax.default_backend(),
                      "idx_decode": decode,
                      "idx_decode_production_path": "python (default: parity "
                      "within noise, no native-build dependency; native "
                      "reader kept for C-ABI tests)"}})


def bench_weight_update_sharding() -> dict:
    """ZeRO-1 cross-replica sharded weight update (arXiv:2004.13336,
    `parallel.shard_weight_update`) vs the replicated update, on the
    flagship CNN with momentum: per-chip optimizer-state bytes (metered
    from the live state's shard shapes) and the weight-update wall time
    (the isolated aggregation+update program,
    parallel.api.build_weight_update_step — model compute would drown
    the signal). Gates: opt-state bytes ≤ (1/n_replica + ε) of
    replicated, and the sharded update's interleaved-repeat median no
    slower than replicated beyond 10%."""
    from distributedmnist_tpu.core.config import ExperimentConfig
    from distributedmnist_tpu.core.mesh import make_topology
    from distributedmnist_tpu.models.registry import get_model
    from distributedmnist_tpu.parallel.api import (
        build_weight_update_step, init_train_state, state_partition_specs)
    from distributedmnist_tpu.train.lr_schedule import constant
    import jax.numpy as jnp

    topo = make_topology()
    n = topo.num_replicas
    if n <= 1:
        # zero1_plan_for no-ops on a 1-replica mesh: both arms would
        # run the identical replicated update and the gate would pass
        # VACUOUSLY — report skipped instead of a hollow green (CI runs
        # this case under a forced 8-device mesh, tier1.yml)
        return {"metric": "weight_update_sharding", "value": None,
                "unit": "per-chip opt-state bytes, sharded/replicated",
                "passes_gate": None,
                "skipped": ("single-replica mesh — the sharding claims "
                            "need n_replica > 1 (force a multi-device "
                            "mesh, e.g. XLA_FLAGS=--xla_force_host_"
                            "platform_device_count=8)"),
                "detail": _env_stamp()}

    def build(shard: bool):
        cfg = ExperimentConfig.from_dict({
            "optim": {"momentum": 0.9},
            "model": {"compute_dtype": "float32"},
            "parallel": {"shard_weight_update": shard},
        })
        model = get_model(cfg.model)
        state = topo.device_put_state(
            init_train_state(model, cfg, topo),
            state_partition_specs(model, cfg, topo))
        upd = build_weight_update_step(model, cfg, topo, constant(1e-3))
        grads = topo.device_put_replicated(
            jax.tree.map(lambda p: np.full(p.shape, 1e-4, np.float32)
                         if hasattr(p, "shape") else p,
                         jax.device_get(state.params)))

        def step_fn(st, g):
            new = upd(st, g)
            # the fetched scalar depends on the update so the timed
            # drain covers the whole program
            return new, {"loss": new.updates_applied.astype(jnp.float32)}
        return state, grads, step_fn

    def opt_state_bytes_per_chip(state) -> int:
        total = 0
        for leaf in jax.tree.leaves(state.momentum):
            shard = leaf.sharding.shard_shape(leaf.shape)
            total += int(np.prod(shard)) * leaf.dtype.itemsize
        return total

    chunk_len, n_chunks, n_repeats = 20, 2, 3
    timers, bytes_per_chip = {}, {}
    for name, shard in (("replicated", False), ("sharded", True)):
        state, grads, step_fn = build(shard)
        bytes_per_chip[name] = opt_state_bytes_per_chip(state)
        timers[name] = _ChunkTimer(step_fn, state, grads, chunk_len)

    rates: dict[str, list[float]] = {name: [] for name in timers}
    for _ in range(n_repeats):  # interleaved: drift lands on both arms
        for name, timer in timers.items():
            dt = sum(timer.measure(n_chunks))
            rates[name].append(chunk_len * n_chunks / dt)

    med = {name: statistics.median(r) for name, r in rates.items()}
    eps = 0.02  # covers flat-layout padding + any sub-floor fallback leaves
    bytes_ratio = bytes_per_chip["sharded"] / bytes_per_chip["replicated"]
    bytes_ok = bytes_ratio <= 1.0 / n + eps
    # updates/sec, higher better; sharded may be FASTER (1/n update
    # FLOPs) — the gate only forbids it being >10% slower
    time_ratio = med["replicated"] / med["sharded"]  # sharded_time/replicated
    time_ok = time_ratio <= 1.10
    return {
        "metric": "weight_update_sharding",
        "value": round(bytes_ratio, 4),
        "unit": "per-chip opt-state bytes, sharded/replicated",
        "passes_gate": bool(bytes_ok and time_ok),
        "detail": {
            "gate": (f"bytes ≤ 1/{n}+{eps} of replicated AND median "
                     f"update time within +10% over {n_repeats} "
                     "interleaved repeats"),
            "n_replicas": n,
            "opt_state_bytes_per_chip": bytes_per_chip,
            "bytes_gate_ok": bool(bytes_ok),
            "update_time_ratio_sharded_vs_replicated": round(time_ratio, 3),
            "time_gate_ok": bool(time_ok),
            "updates_per_sec_median": {k: round(v, 2)
                                       for k, v in med.items()},
            "updates_per_sec_by_repeat": {
                k: [round(r, 2) for r in v] for k, v in rates.items()},
            **_env_stamp()}}


def bench_zero1_overlap() -> dict:
    """Bucketed ZeRO-1 comm overlap (ISSUE 12, arXiv:1810.11112):
    monolithic (comm_buckets=1) vs bucketed (comm_buckets=4) FULL train
    step on the flagship CNN with momentum, interleaved-repeat medians.
    Reports ``overlap_ratio`` = bucketed/monolithic median step time
    (< 1.0 = the regrouped collectives overlapped compute). Gate,
    backend-dependent (the weak_scaling precedent):

      * accelerators — bucketed ≤ 1.0× monolithic: real overlap
        hardware must never lose to the monolithic discipline.
      * CPU mesh — bucketed ≤ 1.05×: the virtual devices' collectives
        serialize on the host, so the claim is PARITY within the
        measured interleaved-repeat noise (readings straddle 1.0 by
        ±2-3% run to run — the r05 cdf lesson; README documents
        "leave buckets at 1 on CPU meshes").

    The lowered StableHLO of both arms is hashed as structural
    evidence (the PR 10 cdf precedent, inverted): the programs
    genuinely differ — bucketed carries fewer, larger collectives —
    so the gate measures a real regrouping, and bitwise-equal
    numerics are pinned separately in tests/test_zero1.py."""
    from distributedmnist_tpu.data.datasets import make_synthetic

    n_dev = len(jax.devices())
    if n_dev <= 1:
        return {"metric": "zero1_overlap", "value": None,
                "unit": "x (bucketed/monolithic median step time)",
                "passes_gate": None,
                "skipped": ("single-replica mesh — comm bucketing needs "
                            "n_replica > 1 (force a multi-device mesh, "
                            "e.g. XLA_FLAGS=--xla_force_host_platform_"
                            "device_count=8)"),
                "detail": _env_stamp()}

    # CI-affordable sizes: the gate is a RATIO of comm disciplines on
    # the same step, not a throughput anchor
    batch = 128 * n_dev
    ds = make_synthetic(num_train=batch, num_test=64)
    host_batch = {"image": ds.train.images[:batch],
                  "label": ds.train.labels[:batch]}
    arms = {"monolithic": 1, "bucketed": 4}
    # back-to-back dispatched steps, NOT the _ChunkTimer scan: XLA's
    # while-loop + collective-scheduling passes make a scanned zero1
    # step pathologically slow to compile on this CPU mesh (measured
    # ~6 min for a 5-step scan vs ~4 s for the step itself). A python
    # dispatch loop drained once per chunk keeps the device queue
    # saturated, which is all a same-host ratio needs.
    chunk_len, n_pairs = 5, 6

    import hashlib

    from distributedmnist_tpu.core.config import ExperimentConfig
    from distributedmnist_tpu.core.mesh import make_topology
    from distributedmnist_tpu.models.registry import get_model
    from distributedmnist_tpu.parallel.api import (
        build_train_step, init_train_state, state_partition_specs)
    from distributedmnist_tpu.train.lr_schedule import constant

    topo = make_topology()
    timers: dict = {}  # arm name -> measure(n_steps) -> wall seconds
    programs: dict[str, dict] = {}
    for name, buckets in arms.items():
        # init WITH the topology: the ZeRO-1 plan shapes the momentum
        # (and state specs) — bench._build's topo-less init would hand
        # the sharded step a replicated-layout state
        cfg = ExperimentConfig.from_dict({
            "data": {"dataset": "synthetic", "batch_size": batch},
            "model": {"compute_dtype": "float32"},
            "optim": {"momentum": 0.9},
            "parallel": {"shard_weight_update": True,
                         "comm_buckets": buckets},
            "sync": {"mode": "sync"},
        })
        model = get_model(cfg.model)
        state = topo.device_put_state(
            init_train_state(model, cfg, topo),
            state_partition_specs(model, cfg, topo))
        step_fn = build_train_step(model, cfg, topo, constant(8e-4))
        gbatch = topo.device_put_batch(host_batch)
        try:
            txt = step_fn.jitted.lower(
                state, gbatch, topo.zeros_measured(),
                step_fn.default_discipline()).as_text()
            programs[name] = {
                "stablehlo_lines": txt.count("\n"),
                "stablehlo_sha256": hashlib.sha256(
                    txt.encode()).hexdigest()[:16]}
        except Exception as e:
            programs[name] = {"error": f"{type(e).__name__}: {e}"}
        # compile + one warm step, then a dispatch-loop runner
        st, m = step_fn(state, gbatch)
        _drain(m)
        holder = {"state": st}

        def measure(n_steps, holder=holder, step_fn=step_fn,
                    gbatch=gbatch):
            st = holder["state"]
            t0 = time.perf_counter()
            for _ in range(n_steps):
                st, m = step_fn(st, gbatch)
            _drain(m)  # the queue ran the steps back-to-back
            holder["state"] = st
            return time.perf_counter() - t0

        timers[name] = measure

    # chunk-level interleave: each PAIR times the two arms back-to-back
    # (seconds apart, not a whole arm-sweep apart) and contributes one
    # bucketed/monolithic ratio — box-level drift over the run cancels
    # within pairs instead of landing on whichever arm ran later (the
    # failure mode arm-granularity interleaving measured here: ±5%
    # repeat drift flipping a ~1.0 ratio)
    rates: dict[str, list[float]] = {name: [] for name in arms}
    pair_ratios: list[float] = []
    for _ in range(n_pairs):
        dt_m = timers["monolithic"](chunk_len)
        dt_b = timers["bucketed"](chunk_len)
        rates["monolithic"].append(chunk_len / dt_m)
        rates["bucketed"].append(chunk_len / dt_b)
        pair_ratios.append(dt_b / dt_m)

    med = {name: statistics.median(r) for name, r in rates.items()}
    overlap_ratio = statistics.median(pair_ratios)  # step-time ratio
    cpu = jax.default_backend() == "cpu"
    bound = 1.05 if cpu else 1.0
    passes = overlap_ratio <= bound
    gate = (("cpu mesh: bucketed median step time ≤ 1.05× monolithic — "
             "host-serialized collectives make the honest claim parity "
             "within the measured ±2-3% repeat noise; accelerators gate "
             "≤ 1.0×") if cpu else
            "accelerator: bucketed median step time ≤ 1.0× monolithic")
    return {
        "metric": "zero1_overlap",
        "value": round(overlap_ratio, 3),
        "unit": "x (bucketed/monolithic median step time)",
        "passes_gate": bool(passes),
        "detail": {
            "gate": (f"{gate}; median of {n_pairs} back-to-back "
                     "chunk-pair ratios"),
            "n_replicas": n_dev, "batch": batch,
            "comm_buckets": arms["bucketed"],
            "ratio_by_pair": [round(r, 3) for r in pair_ratios],
            "steps_per_sec_median": {k: round(v, 3)
                                     for k, v in med.items()},
            "steps_per_sec_by_pair": {
                k: [round(r, 3) for r in v] for k, v in rates.items()},
            # structural evidence the regrouping is real: the two arms
            # lower to DIFFERENT programs (unlike the cdf case, where
            # hash identity proved the overhead was capture noise)
            "program": programs,
            "programs_differ": (
                "error" not in programs.get("monolithic", {"error": 1})
                and "error" not in programs.get("bucketed", {"error": 1})
                and programs["monolithic"] != programs["bucketed"]),
            **_env_stamp()},
    }


def bench_save_stall() -> dict:
    """Donation-safe async checkpoint snapshots (ISSUE 12): the step
    loop's per-save stall, sync host fetch (async_snapshot=false) vs
    async snapshot (true), measured from the journaled
    ``save_stall_ms`` of real Trainer runs over interleaved repeats.

    Gate, backend-dependent (the weak_scaling precedent — the claim is
    about OUR save path, not the host):

      * accelerators — async ≤ 0.5× the sync median: the sync fetch is
        a blocking D2H transfer of the whole state, exactly what the
        async device-side copy removes from the loop.
      * CPU client — ``device_get`` is ZERO-COPY host views here (PJRT
        copy-on-donate covers donation safety), so the sync fetch is
        already nearly free and residual step-drain noise (shared by
        both arms) swamps the 0.5× contrast (measured: medians within
        ~10% either direction). The gated claim is that the async
        machinery adds NO stall: async ≤ 1.0× sync + 1 ms.

    Artifacts stay bitwise identical either way (pinned in
    tests/test_async_checkpoint.py)."""
    import shutil
    import tempfile
    from pathlib import Path

    from distributedmnist_tpu.core.config import ExperimentConfig
    from distributedmnist_tpu.obsv.report import load_jsonl
    from distributedmnist_tpu.train.loop import Trainer

    workdir = Path(tempfile.mkdtemp(prefix="dmt_save_stall_"))
    n_repeats = 3
    stalls: dict[str, list[float]] = {"sync_fetch": [], "async_snapshot": []}
    try:
        def one_run(tag: str, async_snapshot: bool, rep: int) -> list[float]:
            d = workdir / f"{tag}_{rep}"
            cfg = ExperimentConfig.from_dict({
                "data": {"dataset": "synthetic", "batch_size": 64,
                         "use_native_pipeline": False},
                "model": {"compute_dtype": "float32"},
                "optim": {"momentum": 0.9},
                "parallel": {"shard_weight_update": True},
                # log cadence == save cadence: the flush preceding each
                # save drains the in-flight step, so the journaled stall
                # isolates the SAVE machinery (host fetch + canonical
                # conversion vs snapshot dispatch) from residual step
                # execution, which both arms share
                "train": {"max_steps": 8, "log_every_steps": 2,
                          "save_interval_steps": 2,
                          "save_results_period": 0,
                          "train_dir": str(d),
                          "async_checkpoint": True,
                          "async_snapshot": async_snapshot}})
            Trainer(cfg).run()
            return [r["save_stall_ms"]
                    for r in load_jsonl(d / "train_log.jsonl", "save")]

        for rep in range(n_repeats):  # interleaved
            stalls["sync_fetch"] += one_run("sync", False, rep)
            stalls["async_snapshot"] += one_run("async", True, rep)

        med = {k: statistics.median(v) for k, v in stalls.items()}
        ratio = med["async_snapshot"] / med["sync_fetch"]
        cpu = jax.default_backend() == "cpu"
        if cpu:
            passes = (med["async_snapshot"]
                      <= med["sync_fetch"] * 1.0 + 1.0)
            gate = ("cpu client: async-snapshot median save_stall_ms ≤ "
                    "1.0× sync-fetch + 1 ms (zero-copy device_get makes "
                    "the sync fetch ~free here; the gate holds the async "
                    "path to adding no stall — the 0.5× D2H claim gates "
                    "on accelerators)")
        else:
            passes = ratio <= 0.5
            gate = ("accelerator: async-snapshot median save_stall_ms ≤ "
                    "0.5× sync-fetch (the blocking D2H fetch leaves the "
                    "step loop)")
        return {
            "metric": "save_stall",
            "value": round(ratio, 3),
            "unit": "x (async-snapshot/sync-fetch median save stall)",
            "passes_gate": bool(passes),
            "detail": {
                "gate": (f"{gate}; {n_repeats} interleaved Trainer runs, "
                         "stalls read from the journaled save events"),
                "save_stall_ms_median": {k: round(v, 3)
                                         for k, v in med.items()},
                "save_stall_ms_all": {k: [round(x, 3) for x in v]
                                      for k, v in stalls.items()},
                "saves_per_arm": len(stalls["sync_fetch"]),
                **_env_stamp()},
        }
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def bench_checkpoint_durability() -> dict:
    """Storage-shim fsync tax (ISSUE 20): the full atomic-save
    protocol (tmp write → rename → digest sidecar → pointer) under
    ``train.durability=full`` (fsync data + sidecars + directory
    entries) vs ``none`` (the default: rename-atomic, no flush),
    identical state bytes, medians over INTERLEAVED repeats — one
    none/full rotation per repeat, so page-cache and disk drift land
    on both policies alike (the r05 cdf lesson).

    Gate, backend-dependent (the weak_scaling precedent — the claim
    is about OUR save machinery, not the runner's disk):

      * accelerators — full ≤ 3× none median: production NVMe fsyncs
        are sub-ms, so a larger multiple means the shim is flushing
        per-write instead of per-artifact.
      * CPU runners (CI) — full ≤ 10× none + 100 ms absolute: shared
        CI disks put 1-50 ms on every fsync and the none-arm median
        is small enough that the ratio alone is noise; the absolute
        term keeps a save well under any cadence budget while still
        catching per-byte-flush pathologies.

    Crash-consistency itself is not gated here — that is
    tests/test_crash_consistency.py's job; this case prices the knob
    so the README's policy table carries a measured number."""
    import shutil
    import tempfile
    from pathlib import Path

    from distributedmnist_tpu.train import checkpoint as ckpt
    from distributedmnist_tpu.train import storage

    rng = np.random.default_rng(0)
    # flagship-CNN-sized state: ~7 MB of params + momentum
    state = {"params": {f"layer{i}": rng.standard_normal(
                 (256, 256)).astype(np.float32) for i in range(12)},
             "momentum": {f"layer{i}": rng.standard_normal(
                 (256, 256)).astype(np.float32) for i in range(12)},
             "step": np.int32(0)}
    state_bytes = sum(a.nbytes for a in
                      [*state["params"].values(),
                       *state["momentum"].values()])
    workdir = Path(tempfile.mkdtemp(prefix="dmt_durability_"))
    n_repeats, saves_per_repeat = 5, 3
    wall_ms: dict[str, list[float]] = {"none": [], "full": []}
    try:
        step = 0
        for _ in range(n_repeats):  # interleaved: one rotation each
            for policy in ("none", "full"):
                d = workdir / policy
                d.mkdir(exist_ok=True)
                storage.set_durability(policy)
                for _ in range(saves_per_repeat):
                    step += 1
                    t0 = time.perf_counter()
                    ckpt.save_checkpoint(d, state, step)
                    wall_ms[policy].append(
                        (time.perf_counter() - t0) * 1e3)
    finally:
        storage.set_durability("none")
        shutil.rmtree(workdir, ignore_errors=True)

    med = {k: statistics.median(v) for k, v in wall_ms.items()}
    ratio = med["full"] / med["none"]
    extra_ms = med["full"] - med["none"]
    cpu = jax.default_backend() == "cpu"
    if cpu:
        passes = med["full"] <= med["none"] * 10.0 + 100.0
        gate = ("cpu runner: durability=full median save wall ≤ 10× "
                "none + 100 ms (shared CI disks make the bare ratio "
                "noise; the absolute term still catches per-byte "
                "flushing)")
    else:
        passes = ratio <= 3.0
        gate = ("accelerator host: durability=full median save wall "
                "≤ 3× none (NVMe fsyncs are sub-ms — a larger "
                "multiple means the shim flushes per-write, not "
                "per-artifact)")
    return {
        "metric": "checkpoint_durability_overhead",
        "value": round(ratio, 3),
        "unit": "x (durability=full/none median save wall)",
        "passes_gate": bool(passes),
        "detail": {
            "gate": (f"{gate}; medians over {n_repeats} interleaved "
                     f"repeats × {saves_per_repeat} saves"),
            "state_bytes": state_bytes,
            "save_wall_ms_median": {k: round(v, 3)
                                    for k, v in med.items()},
            "fsync_extra_ms_median": round(extra_ms, 3),
            "save_wall_ms_all": {k: [round(x, 2) for x in v]
                                 for k, v in wall_ms.items()},
            "fsync_scope": {"none": "rename-atomic only",
                            "full": "data + sidecar + pointer + "
                                    "directory entries"},
            **_env_stamp()},
    }


def bench_weak_scaling() -> dict:
    """Weak-scaling efficiency of the large-batch playbook (ROADMAP
    item 4, arXiv:1909.09756): images/sec at 1→2→4→8 devices with a
    CONSTANT per-device batch, flagship CNN under the full recipe —
    LAMB + linear-warmup/polynomial-decay schedule + bf16 compute with
    fp32 master weights. Each device count runs on a sub-mesh of the
    same visible devices (the forced mesh in CI), timed with the same
    on-device scan discipline as the headline.

    Gate (at 8 devices), backend-dependent because the claim is about
    OUR step program, not the host:

      * accelerators — the honest weak-scaling floor: img/s at n ≥
        0.6 × n × img/s at 1 (DP allreduce efficiency).
      * CPU backend — n virtual devices on a few cores SERIALIZE at
        every collective rendezvous (capacity ~min(n, cores) is still
        optimistic: measured 24 img/s at n=2 on a 2-core host vs 25 at
        n=1), so the gated claim is that multiplying virtual devices
        does not CRATER total throughput: img/s at 8 ≥ 0.5 × img/s at
        1 (measured 0.72× on this 2-core box). A step program whose
        per-device or collective cost grew superlinearly would fail
        it; a slow runner alone cannot.

    Per-device-count throughput and the raw efficiency curve land in
    the artifact either way."""
    import os

    from distributedmnist_tpu.core.config import MeshConfig
    from distributedmnist_tpu.core.mesh import make_topology
    from distributedmnist_tpu.data.datasets import make_synthetic

    devs = jax.devices()
    counts = [c for c in (1, 2, 4, 8) if c <= len(devs)]
    cpu = jax.default_backend() == "cpu"
    # CPU arms stay CI-affordable: the ratio gate needs matched
    # per-device work across device counts, not a big absolute batch
    per_dev = 64 if cpu else 2048
    chunk_len, n_chunks = (6, 2) if cpu else (50, 4)
    # bf16 is the MXU's native mode but SOFTWARE-emulated in CPU convs
    # (measured ~40× slower at this shape) — the CPU artifact measures
    # the scaling shape in f32 compute, accelerators run the full-bf16
    # recipe; the fp32-master machinery (bf16 param view, f32 update)
    # is exercised either way
    compute = "float32" if cpu else "bfloat16"
    recipe = {
        "optim": {"name": "lamb", "initial_learning_rate": 4e-3,
                  "schedule": "polynomial", "warmup_steps": 20,
                  "decay_total_steps": 2000, "weight_decay": 1e-4},
        "precision": {"param_dtype": "bfloat16", "master_weights": True,
                      "compute_dtype": compute},
    }

    ds = make_synthetic(num_train=per_dev * max(counts), num_test=64)
    rates: dict[int, float] = {}
    compile_s: dict[int, float] = {}
    for n in counts:
        topo = make_topology(MeshConfig(num_replicas=n), devices=devs[:n])
        batch = per_dev * n
        cfg, topo, model, state, step_fn = _build({
            "data": {"dataset": "synthetic", "batch_size": batch},
            "model": {"compute_dtype": compute},
            "sync": {"mode": "sync"},
            **recipe,
        }, topo)
        gbatch = topo.device_put_batch(
            {"image": ds.train.images[:batch],
             "label": ds.train.labels[:batch]})
        times, comp, _ = _scan_chunks(step_fn, state, gbatch,
                                      chunk_len, n_chunks)
        rates[n] = chunk_len * n_chunks * batch / sum(times)
        compile_s[n] = round(comp, 2)
        print(f"# weak_scaling n={n} batch={batch} "
              f"{rates[n]:.0f} img/s", file=sys.stderr)

    n_max = counts[-1]
    eff_curve = {n: round(rates[n] / (n * rates[1]), 3) for n in counts}
    cores = os.cpu_count() or 1
    if cpu:
        floor = 0.5
        gate_metric = rates[n_max] / rates[1]  # no-crater ratio
        gate_desc = (f"cpu backend: img/s at {n_max} virtual devices ≥ "
                     f"{floor}× img/s at 1 (collectives serialize on "
                     f"{cores} core(s); the gate catches superlinear "
                     "per-device/collective cost, not host speed)")
    else:
        floor = 0.6
        gate_metric = eff_curve[n_max]  # true weak-scaling efficiency
        gate_desc = (f"accelerator: img/s at {n_max} devices ≥ {floor}× "
                     f"{n_max}× img/s at 1 (DP allreduce efficiency)")
    gated = n_max >= 8
    passes = bool(gate_metric >= floor) if gated else None
    record = {
        "metric": "weak_scaling_efficiency",
        "value": round(eff_curve[n_max], 3),
        "unit": f"x (img/s at {n_max} dev ÷ {n_max}× img/s at 1 dev)",
        "passes_gate": passes,
        "detail": {
            "gate": gate_desc,
            "gate_metric": round(gate_metric, 3),
            "recipe": recipe,
            "per_device_batch": per_dev,
            "images_per_sec_by_devices": {str(n): round(r, 1)
                                          for n, r in rates.items()},
            "efficiency_by_devices": {str(n): e
                                      for n, e in eff_curve.items()},
            "throughput_ratio_nmax_vs_1": round(rates[n_max] / rates[1], 3),
            "host_cpu_count": cores,
            "compile_s_by_devices": {str(n): c
                                     for n, c in compile_s.items()},
            "compile_s": compile_s[n_max],
            **_env_stamp()},
    }
    if not gated:
        record["skipped_gate"] = (
            f"only {n_max} device(s) visible — the efficiency floor "
            "gates at 8 (force a mesh, e.g. XLA_FLAGS=--xla_force_"
            "host_platform_device_count=8)")
    return record


def bench_restart_latency() -> dict:
    """Restart-latency fast path (ROADMAP item 5), measured end-to-end
    on the local process cluster with REAL ``launch train`` worker
    processes. Three recovery disciplines, same payload (the chaos
    train payload's shape: 2-device simulated mesh, momentum + ZeRO-1):

      * **cold** — spawn with the persistent compile cache DISABLED:
        process boot + full XLA compile + first step.
      * **warm** — spawn against a shared pre-primed compile cache:
        boot + cache deserialize instead of compile.
      * **standby** — promote a parked, precompiled spare: no boot, no
        compile, just adopt-logdir + resume.

    The measured quantity is spawn(or promotion)→first-moved-step — the
    exact recovery leg every supervisor restart and chaos trial pays.
    Gates (vs the cold median): warm ≤ 0.6×, standby ≤ 0.3×. The warm
    gate SKIPS honestly when the platform persisted no cache entries
    during the prime run (nothing to be warm from)."""
    import shutil
    import tempfile
    from pathlib import Path

    from distributedmnist_tpu.launch.cluster import (LocalClusterConfig,
                                                     LocalProcessCluster)
    from distributedmnist_tpu.launch.exec import CommandExecutor, RetryPolicy

    workdir = tempfile.mkdtemp(prefix="dmt_restart_bench_")
    payload = (
        "python -m distributedmnist_tpu.launch train "
        "train.train_dir=. data.dataset=synthetic data.batch_size=32 "
        "data.synthetic_train_size=256 data.synthetic_test_size=64 "
        "model.compute_dtype=float32 mesh.simulate_devices=2 "
        "optim.momentum=0.9 parallel.shard_weight_update=true "
        "train.max_steps=500 train.log_every_steps=1 "
        "train.save_interval_steps=5 train.async_checkpoint=false "
        "train.save_results_period=0")

    def first_step_after(cluster, anchor: float, timeout_s: float = 300.0,
                         keep_log: bool = False) -> float:
        """Seconds from ``anchor`` to the worker's first step record
        stamped at/after it (the artifact timestamps, not poll
        granularity)."""
        from distributedmnist_tpu.obsv.report import load_jsonl
        log = Path(cluster.cfg.worker_dir(0)) / "train_log.jsonl"
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            for rec in load_jsonl(log, "step"):
                if (isinstance(rec.get("time"), (int, float))
                        and rec["time"] >= anchor):
                    return rec["time"] - anchor
            time.sleep(0.25)
        raise RuntimeError(
            f"no step record within {timeout_s:.0f}s of the (re)spawn "
            f"({'existing' if keep_log else 'fresh'} log: {log})")

    def spawn_and_time(cluster) -> float:
        """One cold-ish sample: fresh worker dir, spawn, time to the
        first moved step, then stop the worker."""
        cluster.kill_all()
        wdir = Path(cluster.cfg.worker_dir(0))
        if wdir.exists():
            shutil.rmtree(wdir)
        wdir.mkdir(parents=True)
        cluster.run_train()
        anchor = cluster.status()["workers"][0]["spawned_at"]
        try:
            return first_step_after(cluster, anchor)
        finally:
            cluster.kill_all()

    clusters: list[LocalProcessCluster] = []

    def make_cluster(name: str, cache: bool,
                     standby: bool = False) -> LocalProcessCluster:
        cfg = LocalClusterConfig(
            name=name, num_workers=1, workdir=workdir,
            train_command=payload,
            compile_cache=cache,
            compile_cache_dir=(str(Path(workdir) / "shared_cache")
                               if cache else ""))
        ex = CommandExecutor(journal=cfg.root / "command_journal.jsonl",
                             retry=RetryPolicy(max_attempts=1))
        c = LocalProcessCluster(cfg, ex)
        c.create()
        clusters.append(c)
        return c

    def compile_events(cluster) -> list[dict]:
        from distributedmnist_tpu.obsv.report import load_jsonl
        return load_jsonl(Path(cluster.cfg.worker_dir(0))
                          / "train_log.jsonl", "compile")

    detail: dict = {"payload": payload, **_env_stamp()}
    try:
        # --- cold arm: no cache at all --------------------------------
        cold_cluster = make_cluster("cold", cache=False)
        cold = [spawn_and_time(cold_cluster) for _ in range(3)]
        cold_cluster.delete()
        cold_median = statistics.median(cold)

        # --- warm arm: prime the shared cache, then measure -----------
        from distributedmnist_tpu.core.compile_cache import cache_stats
        warm_cluster = make_cluster("warm", cache=True)
        cache_dir = warm_cluster.cfg.resolved_compile_cache_dir()
        prime = spawn_and_time(warm_cluster)
        primed = cache_stats(cache_dir)
        warm: list[float] = []
        warm_skipped = None
        if primed["entries"] == 0:
            warm_skipped = ("platform persisted no compile-cache "
                            "entries — nothing to be warm from")
        else:
            warm = [spawn_and_time(warm_cluster) for _ in range(2)]
        # dir-level stats only: hit/miss counters are PER PROCESS (they
        # move in the workers, not in this bench process — reporting
        # ours here would upload meaningless zeros); the per-worker
        # hit evidence is worker_compile_events' persistent_cache
        # block (new_entries == 0 on a warm boot)
        cstats = cache_stats(cache_dir)
        detail["compile_cache"] = {
            "dir": cstats["dir"], "entries": cstats["entries"],
            "bytes": cstats["bytes"],
            "entries_after_prime": primed["entries"]}
        detail["worker_compile_events"] = compile_events(warm_cluster)[-1:]

        # --- standby arm: promote parked precompiled spares -----------
        standby: list[float] = []
        for _ in range(2):
            warm_cluster.ensure_standbys(1)
            deadline = time.time() + 300.0
            while time.time() < deadline:
                st = warm_cluster.status()
                if any(sb["ready"] for sb in st.get("standbys", [])):
                    break
                time.sleep(0.5)
            else:
                raise RuntimeError("standby never reached ready")
            warm_cluster.kill_all(worker="0")
            if not warm_cluster.promote_standby(0):
                raise RuntimeError("promote_standby found no ready spare")
            anchor = warm_cluster.status()["workers"][0]["spawned_at"]
            standby.append(first_step_after(warm_cluster, anchor,
                                            keep_log=True))
            warm_cluster.kill_all()
        warm_cluster.delete()

        warm_median = statistics.median(warm) if warm else None
        standby_median = statistics.median(standby)
        warm_ratio = (round(warm_median / cold_median, 3)
                      if warm_median is not None else None)
        standby_ratio = round(standby_median / cold_median, 3)
        warm_ok = None if warm_skipped else bool(warm_ratio <= 0.6)
        standby_ok = bool(standby_ratio <= 0.3)
        detail.update({
            "gate": "warm ≤ 0.6× cold median, standby ≤ 0.3× cold median",
            "cold_s": [round(t, 2) for t in cold],
            "cold_median_s": round(cold_median, 2),
            "prime_s": round(prime, 2),
            "warm_s": [round(t, 2) for t in warm],
            "warm_median_s": (round(warm_median, 2)
                              if warm_median is not None else None),
            "standby_s": [round(t, 2) for t in standby],
            "standby_median_s": round(standby_median, 2),
            "warm_ratio_vs_cold": warm_ratio,
            "standby_ratio_vs_cold": standby_ratio,
            "warm_gate_ok": warm_ok,
            "standby_gate_ok": standby_ok,
        })
        if warm_skipped:
            detail["warm_skipped"] = warm_skipped
        passes = standby_ok and (warm_ok is not False)
        return {"metric": "restart_latency",
                "value": warm_ratio if warm_ratio is not None
                else standby_ratio,
                "unit": "x (restart first-moved-step vs cold median)",
                "passes_gate": bool(passes),
                "detail": detail}
    finally:
        # an error mid-arm must not leak detached worker/standby
        # processes (start_new_session survives us; a parked standby
        # whose activation dir vanished would spin forever) — kill
        # every cluster this run created before removing its workdir
        for c in clusters:
            try:
                c.kill_all()
                c.exec.close()
            except Exception:
                pass
        shutil.rmtree(workdir, ignore_errors=True)


def bench_serving_latency() -> dict:
    """Online serving tier (ROADMAP item 3), gated end-to-end in one
    process: a real ServingReplica (socket, admission queue, bucketed
    batching) under a closed-loop load sweep at fixed offered load,
    with checkpoint publishes landing MID-SWEEP so the zero-drop
    hot-swap is measured, not assumed.

    Two sweeps, same replica, same offered load (closed loop,
    ``concurrency`` in-flight):

      * **steady** — no publishes: the p50/p99 baseline.
      * **swap** — a publisher thread pushes a fresh checkpoint every
        ~300 ms: every request still gets a terminal outcome
        (dropped == 0), at least one hot-swap actually happened
        (≥2 distinct model steps served), and p99 stays bounded
        relative to steady (≤ max(5×, +250 ms) — the swap may cost a
        batch boundary, never a stall).

    The reject rate at this load is reported (expected 0 under the
    default queue depth — admission control only sheds when the queue
    is actually full)."""
    import shutil
    import tempfile
    import threading
    from pathlib import Path

    from distributedmnist_tpu.core.config import ExperimentConfig, ServeConfig
    from distributedmnist_tpu.servesvc.client import ServeClient
    from distributedmnist_tpu.servesvc.loadgen import make_input_fn, run_load
    from distributedmnist_tpu.servesvc.server import ServingReplica
    from distributedmnist_tpu.train.loop import Trainer

    workdir = Path(tempfile.mkdtemp(prefix="dmt_serving_bench_"))
    staging = workdir / "staging"
    publish = workdir / "publish"
    publish.mkdir()
    concurrency, n_requests = 4, 200

    def publish_step(step: int) -> None:
        """Atomically publish one staged checkpoint into the serve
        dir: artifact + digest sidecar first, pointer last (the same
        write order the trainer uses)."""
        name = f"ckpt-{step:08d}.msgpack"
        shutil.copy2(staging / name, publish / name)
        shutil.copy2(staging / (name + ".sha256"),
                     publish / (name + ".sha256"))
        tmp = publish / "checkpoint.json.tmp"
        tmp.write_text(json.dumps({"latest_step": step,
                                   "latest_path": name,
                                   "written_at": time.time()}))
        tmp.replace(publish / "checkpoint.json")

    replica = None
    try:
        # stage a stream of checkpoints (one short deterministic run)
        cfg = ExperimentConfig().override({
            "data.dataset": "synthetic", "data.batch_size": 32,
            "data.synthetic_train_size": 256,
            "data.synthetic_test_size": 64,
            "model.compute_dtype": "float32", "train.max_steps": 60,
            "train.train_dir": str(staging), "train.log_every_steps": 20,
            "train.save_interval_steps": 10,
            "train.async_checkpoint": False,
            "train.save_results_period": 0})
        Trainer(cfg).run()
        staged = sorted(int(p.name[5:13])
                        for p in staging.glob("ckpt-*.msgpack"))
        publish_step(staged[0])

        replica = ServingReplica(
            publish, serve_dir=workdir / "replica",
            scfg=ServeConfig(poll_secs=0.1), cfg=cfg)
        replica.start()
        client = ServeClient([("127.0.0.1", replica.bound_port)],
                             deadline_s=5.0)
        make_input = make_input_fn(
            list(replica.model.input_shape),
            str(np.dtype(replica.model.input_dtype)))

        # warm every bucket shape the sweep can hit (compile once):
        # sequential singles hit bucket 1, the concurrent burst hits
        # the 2/4 buckets the closed loop gathers
        run_load(client, 8, 1, make_input)
        run_load(client, 8 * concurrency, concurrency, make_input)

        steady = run_load(client, n_requests, concurrency, make_input,
                          journal_path=workdir / "loadgen_steady.jsonl")

        stop_pub = threading.Event()

        def publisher() -> None:
            for step in staged[1:]:
                if stop_pub.is_set():
                    return
                time.sleep(0.3)
                publish_step(step)

        pub_thread = threading.Thread(target=publisher, daemon=True)
        swaps_before = replica.swaps
        pub_thread.start()
        swap = run_load(client, n_requests, concurrency, make_input,
                        journal_path=workdir / "loadgen_swap.jsonl")
        stop_pub.set()
        pub_thread.join(timeout=10)
        swaps_during = replica.swaps - swaps_before

        p99_base = steady["latency_ms"]["p99"]
        p99_swap = swap["latency_ms"]["p99"]
        p99_bound = max(5.0 * p99_base, p99_base + 250.0)
        no_drop = (swap["dropped"] == 0 and swap["errors"] == 0
                   and steady["dropped"] == 0)
        swapped = (swaps_during >= 1
                   and len(swap["model_steps_served"]) >= 2)
        p99_ok = p99_swap <= p99_bound
        passes = bool(no_drop and swapped and p99_ok)
        return {
            "metric": "serving_latency",
            "value": p99_swap, "unit": "ms p99 across hot-swaps",
            "passes_gate": passes,
            "detail": {
                "gate": ("zero dropped/errored requests AND >=1 mid-"
                         "sweep hot-swap (>=2 model steps served) AND "
                         "p99_swap <= max(5x, +250ms) of steady p99"),
                "offered_load": {"concurrency": concurrency,
                                 "requests_per_sweep": n_requests},
                "steady": steady, "swap_sweep": swap,
                "swaps_during_sweep": swaps_during,
                "p99_steady_ms": p99_base, "p99_swap_ms": p99_swap,
                "p99_bound_ms": round(p99_bound, 3),
                "no_drop_ok": bool(no_drop),
                "swap_happened_ok": bool(swapped),
                "p99_gate_ok": bool(p99_ok),
                "reject_rate": swap["reject_rate"],
                **_env_stamp()}}
    finally:
        if replica is not None:
            try:
                replica.stop()
            except Exception:
                pass
        shutil.rmtree(workdir, ignore_errors=True)


def bench_degraded_network() -> dict:
    """Serving under transport faults (ISSUE 19): one real replica
    behind the netchaos proxy, gated on **exactly-once outcomes** —
    every request reaches one terminal, duplicates are answered from
    the dedup cache (never re-executed), and the tail stays bounded.

    Two arms, a FRESH replica each (loadgen request ids restart at 0
    per sweep — reusing a replica would let arm 1's dedup cache answer
    arm 2's requests and fake the clean baseline):

      * **clean** — direct connection: the p50/p99 baseline.
      * **degraded** — the same sweep through a ChaosProxy scripted
        with added latency+jitter and a one-shot connection reset that
        cuts the first response mid-wire.  The reset lands AFTER the
        replica computed and cached the outcome (the protocol caches
        before sending), so the client's retry must produce a dedup
        hit, not a second execution.

    Gates: zero drops and zero errors in both arms; the degraded sweep
    retried >= 1 request and the replica served >= 1 dedup hit; no
    request id has more than one ``respond`` execution record in the
    replica's journal (unlicensed duplicate = fail); degraded p99 <=
    max(5x, +500 ms) of clean p99 (retry backoff may cost a round
    trip, never a stall)."""
    import shutil
    import tempfile
    from pathlib import Path

    from distributedmnist_tpu.core.config import ExperimentConfig, ServeConfig
    from distributedmnist_tpu.launch.netchaos import ChaosProxy
    from distributedmnist_tpu.servesvc.client import ServeClient
    from distributedmnist_tpu.servesvc.loadgen import make_input_fn, run_load
    from distributedmnist_tpu.servesvc.server import ServingReplica
    from distributedmnist_tpu.train.loop import Trainer

    workdir = Path(tempfile.mkdtemp(prefix="dmt_netchaos_bench_"))
    staging = workdir / "staging"
    publish = workdir / "publish"
    publish.mkdir()
    concurrency, n_requests = 4, 150

    cfg = ExperimentConfig().override({
        "data.dataset": "synthetic", "data.batch_size": 32,
        "data.synthetic_train_size": 256,
        "data.synthetic_test_size": 64,
        "model.compute_dtype": "float32", "train.max_steps": 20,
        "train.train_dir": str(staging), "train.log_every_steps": 20,
        "train.save_interval_steps": 10,
        "train.async_checkpoint": False,
        "train.save_results_period": 0})
    Trainer(cfg).run()
    name = sorted(staging.glob("ckpt-*.msgpack"))[-1].name
    for suffix in ("", ".sha256"):
        shutil.copy2(staging / (name + suffix), publish / (name + suffix))
    (publish / "checkpoint.json").write_text(json.dumps(
        {"latest_step": int(name[5:13]), "latest_path": name,
         "written_at": time.time()}))

    def run_arm(tag: str, proxy_scripts: list[dict] | None):
        """Boot a fresh replica, warm it DIRECT (string request ids —
        never colliding with the sweep's integer ids), then sweep
        through the proxy (or direct for the clean arm)."""
        replica = ServingReplica(
            publish, serve_dir=workdir / f"replica_{tag}",
            scfg=ServeConfig(poll_secs=0.1), cfg=cfg)
        proxy = None
        try:
            replica.start()
            direct = ("127.0.0.1", replica.bound_port)
            make_input = make_input_fn(
                list(replica.model.input_shape),
                str(np.dtype(replica.model.input_dtype)))
            warm = ServeClient([direct], deadline_s=5.0)
            for i in range(2 * concurrency):
                warm.request(make_input(i), request_id=f"warm-{tag}-{i}")
            ep = direct
            if proxy_scripts is not None:
                proxy = ChaosProxy(direct, proxy_scripts, worker=1,
                                   seed=0)
                ep = ("127.0.0.1", proxy.start())
            client = ServeClient([ep], deadline_s=5.0)
            sweep = run_load(
                client, n_requests, concurrency, make_input,
                journal_path=workdir / f"loadgen_{tag}.jsonl")
            sweep["dedup_hits"] = replica.dedup_hits
            # unlicensed duplicate = one id EXECUTED twice; a journal
            # with two respond records for one id means the dedup
            # cache failed and the model ran the request again
            per_id: dict = {}
            log = workdir / f"replica_{tag}" / "serve_log.jsonl"
            for line in log.read_text().splitlines():
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("action") == "respond":
                    rid = rec.get("id")
                    per_id[rid] = per_id.get(rid, 0) + 1
            sweep["double_executions"] = sum(
                n - 1 for n in per_id.values() if n > 1)
            return sweep
        finally:
            if proxy is not None:
                proxy.stop()
            try:
                replica.stop()
            except Exception:
                pass

    try:
        clean = run_arm("clean", None)
        degraded = run_arm("degraded", [
            {"kind": "latency", "delay_ms": 8.0, "jitter_ms": 4.0},
            # any classifier response is >100 bytes: the one-shot cut
            # always lands mid-response, after the outcome was cached
            {"kind": "reset", "after_bytes": 100}])

        p99_clean = clean["latency_ms"]["p99"]
        p99_deg = degraded["latency_ms"]["p99"]
        p99_bound = max(5.0 * p99_clean, p99_clean + 500.0)
        no_drop = (clean["dropped"] == 0 and clean["errors"] == 0
                   and degraded["dropped"] == 0
                   and degraded["errors"] == 0)
        dedup_ok = (degraded["retried"] >= 1
                    and degraded["dedup_hits"] >= 1)
        no_dupes = (clean["double_executions"] == 0
                    and degraded["double_executions"] == 0)
        p99_ok = p99_deg <= p99_bound
        passes = bool(no_drop and dedup_ok and no_dupes and p99_ok)
        return {
            "metric": "degraded_network",
            "value": p99_deg, "unit": "ms p99 behind chaos proxy",
            "passes_gate": passes,
            "detail": {
                "gate": ("zero dropped/errored requests in both arms "
                         "AND >=1 retry absorbed as a dedup hit AND "
                         "zero double executions AND p99_degraded <= "
                         "max(5x, +500ms) of clean p99"),
                "offered_load": {"concurrency": concurrency,
                                 "requests_per_sweep": n_requests},
                "clean": clean, "degraded": degraded,
                "p99_clean_ms": p99_clean, "p99_degraded_ms": p99_deg,
                "p99_bound_ms": round(p99_bound, 3),
                "no_drop_ok": bool(no_drop),
                "dedup_absorbed_retry_ok": bool(dedup_ok),
                "no_double_execution_ok": bool(no_dupes),
                "p99_gate_ok": bool(p99_ok),
                **_env_stamp()}}
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def bench_quantized_serving() -> dict:
    """Quantized serving path (ROADMAP item 5): the int8 sidecar tier
    vs the fp32 path on real ServingReplicas under the closed-loop
    load sweep, PAIRED with the accuracy-parity oracle so speed can
    never silently buy wrongness.

    Three gated claims:

      * **parity (every backend)** — quantized top-1 on the full eval
        split within ``quant.parity_epsilon`` of full precision, and
        top-1 agreement ≥ 1 − epsilon. The oracle runs the same
        dequantize-in-graph predict the replica serves.
      * **resident weight bytes (every backend)** — the int8 tier's
        on-device weight bytes ≤ 0.35× fp32 (per-channel int8 + f32
        scales + f32 1-D leaves lands ~0.25×; the bound catches a
        quantizer that silently stopped quantizing).
      * **throughput/p99 (accelerators only)** — int8 throughput-per-
        replica ≥ fp32 and p99 ≤ fp32 over interleaved sweep pairs.
        On a CPU backend int8 matmuls are software-emulated (the
        dequant multiply is pure extra work with no int8 compute
        units behind it), so the perf half honest-skips — the
        weak_scaling CPU-arm precedent — and the sweeps are reported,
        not gated.
    """
    import shutil
    import tempfile
    from pathlib import Path

    from distributedmnist_tpu.core.config import ExperimentConfig, ServeConfig
    from distributedmnist_tpu.servesvc.client import ServeClient
    from distributedmnist_tpu.servesvc.loadgen import make_input_fn, run_load
    from distributedmnist_tpu.servesvc.server import ServingReplica
    from distributedmnist_tpu.train import checkpoint as ckpt
    from distributedmnist_tpu.train.loop import Trainer

    workdir = Path(tempfile.mkdtemp(prefix="dmt_quant_bench_"))
    staging = workdir / "staging"
    publish = workdir / "publish"
    publish.mkdir()
    concurrency, n_requests, n_pairs = 4, 120, 2
    epsilon = 0.02

    def publish_step(step: int) -> None:
        names = [f"ckpt-{step:08d}.msgpack", f"ckpt-{step:08d}.quant.msgpack"]
        for name in names:
            for sfx in ("", ".sha256"):
                shutil.copy2(staging / (name + sfx), publish / (name + sfx))
        tmp = publish / "checkpoint.json.tmp"
        tmp.write_text(json.dumps({"latest_step": step,
                                   "latest_path": names[0],
                                   "written_at": time.time()}))
        tmp.replace(publish / "checkpoint.json")

    replicas = {}
    try:
        cfg = ExperimentConfig().override({
            "data.dataset": "synthetic", "data.batch_size": 64,
            "data.synthetic_train_size": 1024,
            "data.synthetic_test_size": 512,
            "data.use_native_pipeline": False,
            "model.compute_dtype": "float32", "train.max_steps": 30,
            "train.train_dir": str(staging), "train.log_every_steps": 10,
            "train.save_interval_steps": 10,
            "train.async_checkpoint": False,
            "train.save_results_period": 0,
            "quant.publish_tiers": "int8",
            "quant.parity_epsilon": epsilon})
        trainer = Trainer(cfg)
        trainer.run()
        step = max(int(p.name[5:13]) for p in staging.glob("ckpt-*.msgpack")
                   if not p.name.endswith(".quant.msgpack"))
        publish_step(step)
        meta_side = ckpt.read_quant_sidecar(staging, step)["meta"]

        for tier in ("fp32", "int8"):
            rep = ServingReplica(
                publish, serve_dir=workdir / f"replica_{tier}",
                scfg=ServeConfig(poll_secs=0.1, precision_tier=tier),
                cfg=cfg)
            rep.start()
            replicas[tier] = rep
        clients = {t: ServeClient([("127.0.0.1", r.bound_port)],
                                  deadline_s=5.0)
                   for t, r in replicas.items()}
        meta_probe = {t: {k: (c.meta() or {}).get(k)
                          for k in ("precision_tier", "active_tier",
                                    "tier_source_digest")}
                      for t, c in clients.items()}
        make_input = make_input_fn(
            list(replicas["fp32"].model.input_shape),
            str(np.dtype(replicas["fp32"].model.input_dtype)))

        # warm every bucket shape both arms can hit (compile once)
        for c in clients.values():
            run_load(c, 8, 1, make_input)
            run_load(c, 8 * concurrency, concurrency, make_input)

        # interleaved sweep pairs: box drift cancels within a pair
        sweeps: dict[str, list[dict]] = {"fp32": [], "int8": []}
        for _ in range(n_pairs):
            for tier in ("fp32", "int8"):
                sweeps[tier].append(run_load(
                    clients[tier], n_requests, concurrency, make_input))
        rps = {t: statistics.median(s["throughput_rps"] for s in v)
               for t, v in sweeps.items()}
        p99 = {t: statistics.median(s["latency_ms"]["p99"] for s in v)
               for t, v in sweeps.items()}
        dropped = sum(s["dropped"] + s["errors"]
                      for v in sweeps.values() for s in v)

        # -- the accuracy-parity oracle on the FULL eval split --------
        # the same installed weights + predict fns the replicas serve
        x_eval = trainer.datasets.test.images
        labels = trainer.datasets.test.labels
        probs = {}
        for tier, rep in replicas.items():
            probs[tier] = np.asarray(jax.device_get(
                rep._predict(rep._params, x_eval)))
        from distributedmnist_tpu.quant.ptq import parity_report
        parity = parity_report(probs["fp32"], probs["int8"], labels)
        parity_ok = (parity["top1_tier"] >= parity["top1_ref"] - epsilon
                     and parity["agreement"] >= 1.0 - epsilon)

        # -- resident weight bytes (the memory lever, every backend) --
        pbytes = meta_side["param_bytes"]
        bytes_ratio = pbytes["int8"] / pbytes["fp32"]
        bytes_ok = bytes_ratio <= 0.35

        cpu = jax.default_backend() == "cpu"
        tiers_measured = {t: sorted({tier for s in v
                                     for tier in s.get("tiers_served", [])})
                          for t, v in sweeps.items()}
        served_right_tier = tiers_measured["int8"] == ["int8"]
        if cpu:
            perf_ok = None  # honest skip: no int8 compute units to win on
            perf_note = ("cpu backend software-emulates int8 (the "
                         "dequant multiply is pure extra work) — "
                         "throughput/p99 reported, gated on "
                         "accelerators only; weak_scaling CPU-arm "
                         "precedent")
        else:
            perf_ok = bool(rps["int8"] >= rps["fp32"]
                           and p99["int8"] <= p99["fp32"])
            perf_note = ("accelerator: int8 throughput-per-replica ≥ "
                         "fp32 AND p99 ≤ fp32 (interleaved sweep "
                         "medians)")
        passes = bool(parity_ok and bytes_ok and served_right_tier
                      and dropped == 0 and perf_ok is not False)
        return {
            "metric": "quantized_serving",
            "value": round(rps["int8"] / rps["fp32"], 3),
            "unit": "x (int8/fp32 throughput-per-replica)",
            "passes_gate": passes,
            "detail": {
                "gate": ("parity: int8 top-1 within ±%.3f of fp32 on "
                         "the eval split AND agreement ≥ %.3f; bytes: "
                         "int8 resident weights ≤ 0.35× fp32; perf: %s"
                         % (epsilon, 1 - epsilon, perf_note)),
                "parity": parity, "parity_gate_ok": bool(parity_ok),
                "epsilon": epsilon,
                "param_bytes": pbytes,
                "int8_bytes_ratio": round(bytes_ratio, 4),
                "bytes_gate_ok": bool(bytes_ok),
                "throughput_rps_median": {k: round(v, 2)
                                          for k, v in rps.items()},
                "p99_ms_median": p99,
                "perf_gate_ok": perf_ok,
                "dropped_or_errored": dropped,
                "offered_load": {"concurrency": concurrency,
                                 "requests_per_sweep": n_requests,
                                 "pairs": n_pairs},
                # which tier each sweep ACTUALLY measured (the meta
                # probe + per-response tier records — satellite: a
                # loadgen artifact must say what it swept)
                "tiers_measured": tiers_measured,
                "meta_probe": meta_probe,
                "calibration": meta_side.get("calibration"),
                **_env_stamp()}}
    finally:
        for rep in replicas.values():
            try:
                rep.stop()
            except Exception:
                pass
        shutil.rmtree(workdir, ignore_errors=True)


def _paged_longcontext_arm() -> dict:
    """Paged vs dense decode_step at short and LONG max-context — the
    micro-arm behind the paged kernel's O(actual) vs O(max) claim.

    Both arms hold the ACTUAL context at ~64 tokens; what differs is
    the provisioned table width (4 blocks vs 68 blocks ≙ 1088-token
    max context).  The dense path gathers every table entry — its
    per-token traffic scales with the WIDTH — while the paged kernel
    masks dead entries to the null block and (compiled) skips their
    DMAs, so its cost tracks the live blocks only.

    Parity between the kernels gates on EVERY backend (the interpret-
    mode kernel runs the same index arithmetic as compiled TPU).  The
    speed gates (paged >= ~dense at width-4; paged >= 2x dense at
    width-68) only apply on accelerators: on CPU the Pallas kernel
    runs interpreted — honestly reported as skipped, never faked by
    timing the interpreter.
    """
    import functools

    import jax.numpy as jnp

    from distributedmnist_tpu.core.config import ModelConfig
    from distributedmnist_tpu.models.registry import get_model
    from distributedmnist_tpu.servesvc.kv_cache import PagedKVCache

    cpu = jax.default_backend() == "cpu"
    heads, hd, layers, slots, vocab = 4, 16, 2, 4, 32
    model = get_model(ModelConfig(
        name="transformer", seq_len=1152, model_dim=heads * hd,
        num_heads=heads, num_layers=layers, vocab_size=vocab,
        compute_dtype="float32", attention_impl="dense"))
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    iters = 2 if cpu else 20
    arms: dict = {}
    parity_ok = True
    speed: dict = {}
    for arm, width in (("short_ctx_64", 4), ("long_ctx_1088", 68)):
        bs, length = 16, 63
        cache = PagedKVCache(
            num_layers=layers, num_blocks=slots * width + 2,
            block_size=bs, num_heads=heads, head_dim=hd,
            max_blocks_per_seq=width)
        tables = np.zeros((slots, width), np.int32)
        for s in range(slots):
            t = cache.alloc_sequence(length + 1)
            tables[s] = t
            toks = jnp.asarray(rng.integers(0, vocab, size=(1, length)),
                               jnp.int32)
            _, ks, vs = model.decode_prefill(params, toks)
            cache.write_prompt(t, ks[:, 0], vs[:, 0], length)
        tables_dev = jnp.asarray(tables)
        tokens = jnp.asarray(rng.integers(0, vocab, size=(slots,)),
                             jnp.int32)
        positions = jnp.full((slots,), length, jnp.int32)
        lengths = jnp.full((slots,), length + 1, jnp.int32)
        out = {}
        ms = {}
        for kern in ("paged", "dense"):
            step = jax.jit(functools.partial(
                model.decode_step, block_size=bs, attention_kernel=kern))
            logits, _, _ = step(params, tokens, positions, cache.k,
                                cache.v, tables_dev, lengths)
            jax.block_until_ready(logits)   # compile outside the clock
            t0 = time.perf_counter()
            for _ in range(iters):
                logits, _, _ = step(params, tokens, positions, cache.k,
                                    cache.v, tables_dev, lengths)
            jax.block_until_ready(logits)
            ms[kern] = (time.perf_counter() - t0) * 1e3 / iters
            out[kern] = np.asarray(logits)
        diff = float(np.max(np.abs(out["paged"] - out["dense"])))
        arm_parity = diff <= 1e-4
        parity_ok = parity_ok and arm_parity
        arms[arm] = {"table_width_blocks": width,
                     "actual_context_tokens": length + 1,
                     "paged_ms_per_step": round(ms["paged"], 3),
                     "dense_ms_per_step": round(ms["dense"], 3),
                     "dense_over_paged": round(ms["dense"] / ms["paged"],
                                               3),
                     "parity_max_abs_diff": diff,
                     "parity_ok": arm_parity}
        speed[arm] = ms
    if cpu:
        speed_gate_ok = None
        speed_note = ("skipped (cpu backend: the pallas kernel runs "
                      "in interpret mode — timing the interpreter "
                      "would fake the claim either way)")
    else:
        short_ok = (speed["short_ctx_64"]["paged"]
                    <= 1.06 * speed["short_ctx_64"]["dense"])
        long_ok = (speed["long_ctx_1088"]["dense"]
                   >= 2.0 * speed["long_ctx_1088"]["paged"])
        speed_gate_ok = bool(short_ok and long_ok)
        speed_note = ("paged >= ~dense at width 4, paged >= 2x dense "
                      "at width 68")
    return {"arms": arms, "parity_ok": bool(parity_ok),
            "speed_gate_ok": speed_gate_ok, "speed_gate": speed_note,
            "iters_per_arm": iters}


def bench_decode_throughput() -> dict:
    """Continuous-batching decode service, gated end-to-end in one
    process: a real DecodeReplica (socket, bounded admission, paged KV
    cache, streaming) under the closed-loop generate loadgen, with
    checkpoint publishes landing MID-SWEEP so the swap-during-
    generation policy is measured, not assumed.

    Two sweeps, same replica, same offered load:

      * **steady** — no publishes: the tokens/s + TTFT baseline.
      * **swap** — a publisher thread pushes fresh checkpoints every
        ~300 ms mid-generation.

    Gated claims (platform-independent — about OUR decode path):

      * zero dropped/errored requests across both sweeps, every
        response actually streamed tokens;
      * continuous batching really refilled: sequences finished >
        decode_slots (slots turned over instead of running one padded
        round);
      * ≥1 hot-swap landed mid-sweep AND the pin policy held — zero
        ``decode_swap`` violations replayed from the replica's own
        journal (no sequence finished on weights it didn't start on);
      * p99 time-to-first-token under swaps bounded relative to steady
        (≤ max(5×, +250 ms) — a swap costs a loop boundary, never a
        stall).

    Absolute tokens/s is REPORTED (the artifact's trajectory metric);
    it gates nowhere on CPU — the decode matmuls here are host-
    serialized, the honest weak_scaling/quantized_serving precedent.

    Two riders ship in the detail: the **long_context** micro-arm
    (paged vs dense decode_step at 4-block and 68-block table widths —
    kernel parity gates on every backend, the speed claims only on
    accelerators where the kernel compiles), and **table_prep** (the
    block-table upload cache's hit accounting vs the measured cost of
    the naive per-step rebuild it replaced).
    """
    import shutil
    import tempfile
    import threading
    from pathlib import Path

    from distributedmnist_tpu.core.config import (DecodeConfig,
                                                  ExperimentConfig,
                                                  ServeConfig)
    from distributedmnist_tpu.obsv.invariants import check_serving
    from distributedmnist_tpu.servesvc.client import ServeClient
    from distributedmnist_tpu.servesvc.decode import DecodeReplica
    from distributedmnist_tpu.servesvc.loadgen import (make_prompt_fn,
                                                       run_load)
    from distributedmnist_tpu.train.loop import Trainer

    workdir = Path(tempfile.mkdtemp(prefix="dmt_decode_bench_"))
    staging = workdir / "staging"
    publish = workdir / "publish"
    publish.mkdir()
    concurrency, n_requests = 4, 60

    def publish_step(step: int) -> None:
        name = f"ckpt-{step:08d}.msgpack"
        shutil.copy2(staging / name, publish / name)
        shutil.copy2(staging / (name + ".sha256"),
                     publish / (name + ".sha256"))
        tmp = publish / "checkpoint.json.tmp"
        tmp.write_text(json.dumps({"latest_step": step,
                                   "latest_path": name,
                                   "written_at": time.time()}))
        tmp.replace(publish / "checkpoint.json")

    replica = None
    try:
        cfg = ExperimentConfig().override({
            "data.dataset": "synthetic_lm", "data.batch_size": 32,
            "data.synthetic_train_size": 256,
            "data.synthetic_test_size": 64,
            "data.use_native_pipeline": False,
            "model.name": "transformer", "model.seq_len": 64,
            "model.model_dim": 64, "model.num_heads": 4,
            "model.num_layers": 2, "model.vocab_size": 32,
            "model.compute_dtype": "float32",
            "model.attention_impl": "dense",
            "train.max_steps": 60, "train.train_dir": str(staging),
            "train.log_every_steps": 20,
            "train.save_interval_steps": 10,
            "train.async_checkpoint": False,
            "train.save_results_period": 0})
        Trainer(cfg).run()
        staged = sorted(int(p.name[5:13])
                        for p in staging.glob("ckpt-*.msgpack"))
        publish_step(staged[0])

        dcfg = DecodeConfig(decode_slots=4, block_size=8, num_blocks=64,
                            max_prompt_len=16, max_new_tokens=16)
        replica = DecodeReplica(
            publish, serve_dir=workdir / "replica",
            scfg=ServeConfig(poll_secs=0.1), dcfg=dcfg, cfg=cfg)
        replica.start()
        client = ServeClient([("127.0.0.1", replica.bound_port)],
                             deadline_s=20.0)
        make_prompt = make_prompt_fn(cfg.model.vocab_size,
                                     dcfg.max_prompt_len)

        # warm the compiled shapes before anything is timed: one
        # request per prompt bucket (every pow-2 up to max_prompt_len
        # — prefill compiles per bucket) plus a concurrent burst for
        # the decode step itself
        bucket = 1
        while bucket <= dcfg.max_prompt_len:
            out = client.generate([1] * bucket, max_tokens=2)
            assert out.get("status") == "ok", out
            bucket *= 2
        run_load(client, 2 * concurrency, concurrency, make_prompt,
                 decode=True)

        steady = run_load(client, n_requests, concurrency, make_prompt,
                          journal_path=workdir / "loadgen_steady.jsonl",
                          decode=True)

        stop_pub = threading.Event()

        def publisher() -> None:
            for step in staged[1:]:
                if stop_pub.is_set():
                    return
                time.sleep(0.3)
                publish_step(step)

        pub_thread = threading.Thread(target=publisher, daemon=True)
        swaps_before = replica.swaps
        finished_before = replica.sequences_finished
        pub_thread.start()
        swap = run_load(client, n_requests, concurrency, make_prompt,
                        journal_path=workdir / "loadgen_swap.jsonl",
                        decode=True)
        stop_pub.set()
        pub_thread.join(timeout=10)
        swaps_during = replica.swaps - swaps_before
        finished_during = replica.sequences_finished - finished_before

        # block-table prep accounting (the per-iteration host rebuild
        # used to be paid on EVERY decode step; now it is cached per
        # (version, epoch) and only re-uploaded when composition
        # changes) — counters from the replica that just served, plus
        # a micro-measure of what ONE naive rebuild costs
        table_uploads = replica.table_uploads
        table_reuses = replica.table_upload_reuses
        width = dcfg.max_blocks_per_seq()
        t0 = time.perf_counter()
        reb_iters = 200
        for _ in range(reb_iters):
            t_np = np.zeros((dcfg.decode_slots, width), np.int32)
            jax.block_until_ready(jax.numpy.asarray(t_np))
        naive_rebuild_ms = ((time.perf_counter() - t0) * 1e3
                            / reb_iters)

        # stop BEFORE replaying the journal (flushes + closes it);
        # the shared finally below is a no-op for a stopped replica
        replica.stop()

        # replay the swap-during-generation invariant over the
        # replica's own journal — the policy gate is the checker, not
        # a bespoke assertion
        trial = workdir / "trial"
        (trial / "worker1").mkdir(parents=True)
        shutil.copy2(workdir / "replica" / "serve_log.jsonl",
                     trial / "worker1" / "serve_log.jsonl")
        violations, _, _, decode_applicable = check_serving(
            trial, {"serve_workers": [1]}, [])
        policy_violations = [v.to_dict() for v in violations
                             if v.invariant == "decode_swap"]

        # paged-vs-dense long-context micro-arm (parity gates
        # everywhere; speed gates on accelerators only)
        long_context = _paged_longcontext_arm()

        ttft_base = steady["ttft_ms"]["p99"]
        ttft_swap = swap["ttft_ms"]["p99"]
        ttft_bound = max(5.0 * ttft_base, ttft_base + 250.0)
        no_drop = all(s["dropped"] == 0 and s["errors"] == 0
                      for s in (steady, swap))
        all_streamed = (steady.get("tokens_streamed", 0) > 0
                        and swap.get("tokens_streamed", 0) > 0
                        and steady["responses"] == n_requests
                        and swap["responses"] == n_requests)
        refilled = finished_during > dcfg.decode_slots
        swapped = swaps_during >= 1
        policy_ok = decode_applicable and not policy_violations
        ttft_ok = ttft_swap <= ttft_bound
        paged_ok = (long_context["parity_ok"]
                    and long_context["speed_gate_ok"] is not False)
        passes = bool(no_drop and all_streamed and refilled and swapped
                      and policy_ok and ttft_ok and paged_ok)
        cpu = jax.default_backend() == "cpu"
        return {
            "metric": "decode_throughput",
            "value": swap.get("tokens_per_sec"),
            "unit": "tokens/sec under hot-swaps",
            "passes_gate": passes,
            "detail": {
                "gate": ("zero dropped/errored, every response "
                         "streamed, continuous refill (> slots "
                         "sequences finished mid-sweep), >=1 mid-"
                         "sweep swap with zero decode_swap "
                         "violations, ttft_p99_swap <= max(5x, "
                         "+250ms) steady; absolute tokens/s "
                         + ("reported only (cpu backend: host-"
                            "serialized decode matmuls)" if cpu
                            else "reported (no accelerator anchor "
                                 "yet)")),
                "offered_load": {"concurrency": concurrency,
                                 "requests_per_sweep": n_requests},
                "decode": {"slots": dcfg.decode_slots,
                           "block_size": dcfg.block_size,
                           "num_blocks": dcfg.num_blocks,
                           "max_new_tokens": dcfg.max_new_tokens,
                           "swap_policy": dcfg.swap_policy},
                "steady": steady, "swap_sweep": swap,
                "swaps_during_sweep": swaps_during,
                "sequences_finished_during_sweep": finished_during,
                "ttft_p99_steady_ms": ttft_base,
                "ttft_p99_swap_ms": ttft_swap,
                "ttft_bound_ms": round(ttft_bound, 3),
                "no_drop_ok": bool(no_drop),
                "all_streamed_ok": bool(all_streamed),
                "refill_ok": bool(refilled),
                "swap_happened_ok": bool(swapped),
                "policy_ok": bool(policy_ok),
                "decode_swap_violations": policy_violations,
                "ttft_gate_ok": bool(ttft_ok),
                "paged_kernel_ok": bool(paged_ok),
                "long_context": long_context,
                "table_prep": {
                    "uploads": table_uploads,
                    "reuses": table_reuses,
                    "reuse_ratio": round(
                        table_reuses / max(1, table_uploads
                                           + table_reuses), 4),
                    "naive_rebuild_ms_per_step": round(
                        naive_rebuild_ms, 4)},
                **_env_stamp()}}
    finally:
        # one cleanup path for every exit (training/boot/sweep
        # failures included) — the quantized_serving pattern
        if replica is not None:
            try:
                replica.stop()
            except Exception:
                pass
        shutil.rmtree(workdir, ignore_errors=True)


def bench_tp_serving() -> dict:
    """Tensor-parallel serving groups under fire: two 2-rank TP decode
    replicas (real ``launch serve --tp-ranks 2`` process groups behind
    the unchanged socket contract), a failover client across both, a
    checkpoint publisher pushing hot-swaps mid-sweep, and a SIGKILL of
    one rank of group 1 mid-generation.

    Gated claims:

      * zero dropped/errored requests across both sweeps — the rank
        kill takes its whole group down (die-as-a-unit) and the CLIENT
        still reaches a terminal outcome for every request via
        failover to the surviving group;
      * the killed group's journal chain replays clean through the
        ``serve_group`` invariant (rank_exit → group_down →
        group_restart → group_start) and the restarted group actually
        serves again;
      * ≥1 hot-swap landed on the surviving group mid-sweep, with the
        serving invariants (outcomes/digest/monotone/decode_swap)
        green on replay;
      * follower ranks journaled ``shard_verify`` — the shard-wise
        digest evidence that hot-swap staging under TP verified the
        bytes each rank holds.

    Tokens/s is reported, never gated: on CPU the "TP" mesh is
    virtual devices and collectives are host-serialized.
    """
    import os
    import shutil
    import signal as _signal
    import subprocess
    import tempfile
    import threading
    from pathlib import Path

    from distributedmnist_tpu.core.config import ExperimentConfig
    from distributedmnist_tpu.obsv.invariants import (check_serve_group,
                                                      check_serving)
    from distributedmnist_tpu.servesvc.client import (ServeClient,
                                                      discover_endpoints)
    from distributedmnist_tpu.servesvc.loadgen import (make_prompt_fn,
                                                       run_load)
    from distributedmnist_tpu.train.loop import Trainer

    workdir = Path(tempfile.mkdtemp(prefix="dmt_tp_bench_"))
    staging = workdir / "staging"
    publish = workdir / "publish"
    publish.mkdir()
    trial = workdir / "trial"
    supervisors: list[subprocess.Popen] = []
    concurrency, n_requests = 3, 24

    def publish_step(step: int) -> None:
        name = f"ckpt-{step:08d}.msgpack"
        shutil.copy2(staging / name, publish / name)
        shutil.copy2(staging / (name + ".sha256"),
                     publish / (name + ".sha256"))
        tmp = publish / "checkpoint.json.tmp"
        tmp.write_text(json.dumps({"latest_step": step,
                                   "latest_path": name,
                                   "written_at": time.time()}))
        tmp.replace(publish / "checkpoint.json")

    def wait_for(pred, timeout_s: float, what: str) -> None:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if pred():
                return
            time.sleep(0.25)
        raise RuntimeError(f"timed out after {timeout_s:.0f}s "
                           f"waiting for {what}")

    def group_actions(k: int) -> list:
        p = trial / f"worker{k}" / "group_log.jsonl"
        if not p.exists():
            return []
        return [json.loads(l).get("action")
                for l in p.read_text().splitlines() if l.strip()]

    try:
        cfg = ExperimentConfig().override({
            "data.dataset": "synthetic_lm", "data.batch_size": 32,
            "data.synthetic_train_size": 256,
            "data.synthetic_test_size": 64,
            "data.use_native_pipeline": False,
            "model.name": "transformer", "model.seq_len": 64,
            "model.model_dim": 64, "model.num_heads": 4,
            "model.num_layers": 2, "model.vocab_size": 32,
            "model.compute_dtype": "float32",
            "model.attention_impl": "dense",
            "train.max_steps": 40, "train.train_dir": str(staging),
            "train.log_every_steps": 20,
            "train.save_interval_steps": 10,
            "train.async_checkpoint": False,
            "train.save_results_period": 0})
        Trainer(cfg).run()
        staged = sorted(int(p.name[5:13])
                        for p in staging.glob("ckpt-*.msgpack"))
        publish_step(staged[0])

        for k in (1, 2):
            serve_dir = trial / f"worker{k}"
            serve_dir.mkdir(parents=True, exist_ok=True)
            supervisors.append(subprocess.Popen(
                [sys.executable, "-m", "distributedmnist_tpu.launch",
                 "serve", "--train_dir", str(publish),
                 "--serve-dir", str(serve_dir), "--port", "0",
                 "--poll-secs", "0.2", "--queue-depth", "16",
                 "--decode", "--decode-slots", "4",
                 "--max-new-tokens", "8", "--max-prompt-len", "16",
                 "--tp-ranks", "2"],
                env=dict(os.environ)))
        wait_for(lambda: len(discover_endpoints(trial)) == 2, 600,
                 "both TP groups' serve.json")

        client = ServeClient(lambda: discover_endpoints(trial),
                             deadline_s=120.0, max_attempts=8)
        make_prompt = make_prompt_fn(cfg.model.vocab_size, 16)
        # warm every prompt bucket on BOTH replicas (round-robin:
        # two requests per bucket) before anything is timed or killed
        bucket = 1
        while bucket <= 16:
            for _ in range(2):
                out = client.generate([1] * bucket, max_tokens=2)
                assert out.get("status") == "ok", out
            bucket *= 2

        steady = run_load(client, n_requests, concurrency, make_prompt,
                          journal_path=workdir / "loadgen_steady.jsonl",
                          decode=True)

        # sweep B: publisher pushes swaps while one rank of group 1 is
        # murdered mid-generation
        stop_pub = threading.Event()

        def publisher() -> None:
            for step in staged[1:]:
                if stop_pub.is_set():
                    return
                time.sleep(0.4)
                publish_step(step)

        kill_info: dict = {}

        def killer() -> None:
            time.sleep(1.0)
            roster = json.loads(
                (trial / "worker1" / "group.json").read_text())
            pid = int(roster["pids"]["1"])     # a non-zero rank
            try:
                os.kill(pid, _signal.SIGKILL)
                kill_info["killed_pid"] = pid
            except OSError as e:
                kill_info["error"] = str(e)

        pub_t = threading.Thread(target=publisher, daemon=True)
        kill_t = threading.Thread(target=killer, daemon=True)
        pub_t.start()
        kill_t.start()
        swap = run_load(client, n_requests, concurrency, make_prompt,
                        journal_path=workdir / "loadgen_swap.jsonl",
                        decode=True)
        stop_pub.set()
        pub_t.join(timeout=10)
        kill_t.join(timeout=10)

        # the murdered group must come back as a UNIT and serve again
        wait_for(lambda: "group_restart" in group_actions(1), 120,
                 "group 1's unit restart in its journal")
        wait_for(lambda: (trial / "worker1" / "serve.json").exists(),
                 600, "restarted group 1 republishing its endpoint")
        ep = json.loads((trial / "worker1" / "serve.json").read_text())
        confirm = ServeClient([(ep["host"], int(ep["port"]))],
                              deadline_s=240.0, max_attempts=2)
        out = confirm.generate([1, 2, 3], max_tokens=2)
        restarted_serves = out.get("status") == "ok"

        # graceful teardown BEFORE replay so every journal is flushed
        for p in supervisors:
            if p.poll() is None:
                p.send_signal(_signal.SIGTERM)
        for p in supervisors:
            try:
                p.wait(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()

        # ---- replay ----------------------------------------------------
        # the rank kill is a journaled fault: worker 1's server-side
        # admit/terminal mismatch is exempt (its in-flight admissions
        # died with the group); the CLIENT-side zero-drop gate is what
        # proves failover covered them
        fault_records = [{"event": "fault", "action": "kill_worker",
                          "worker": 1, "ts": time.time()}]
        violations, applicable, _, decode_applicable = check_serving(
            trial, {"serve_workers": [1, 2]}, fault_records)
        group_violations, group_applicable = check_serve_group(trial)

        acts = group_actions(1)
        i_exit = acts.index("rank_exit") if "rank_exit" in acts else -1
        chain_ok = (i_exit >= 0
                    and "group_down" in acts[i_exit:]
                    and "group_restart" in acts[i_exit:]
                    and acts.count("group_start") >= 2)
        shard_verified = 0
        for k in (1, 2):
            rlog = trial / f"worker{k}" / "rank1" / "serve_log.jsonl"
            if rlog.exists():
                shard_verified += sum(
                    1 for l in rlog.read_text().splitlines() if l.strip()
                    and json.loads(l).get("action") == "shard_verify")
        swaps = 0
        for k in (1, 2):
            slog = trial / f"worker{k}" / "serve_log.jsonl"
            swaps += sum(
                1 for l in slog.read_text().splitlines() if l.strip()
                and json.loads(l).get("action") == "weight_swap"
                and not json.loads(l).get("initial"))

        no_drop = all(s["dropped"] == 0 and s["errors"] == 0
                      for s in (steady, swap))
        all_responded = (steady["responses"] == n_requests
                         and swap["responses"] == n_requests)
        invariants_ok = (applicable and decode_applicable
                         and group_applicable and not violations
                         and not group_violations)
        passes = bool(no_drop and all_responded and chain_ok
                      and restarted_serves and swaps >= 1
                      and shard_verified >= 1 and invariants_ok
                      and "killed_pid" in kill_info)
        return {
            "metric": "tp_serving",
            "value": swap.get("tokens_per_sec"),
            "unit": "tokens/sec through a rank kill + hot-swaps",
            "passes_gate": passes,
            "detail": {
                "gate": ("zero dropped/errored requests through a "
                         "mid-sweep SIGKILL of one TP rank (group died "
                         "as a unit, client failed over, group "
                         "restarted and served) + >=1 hot-swap with "
                         "serving/serve_group invariants green on "
                         "replay + follower shard_verify digests "
                         "journaled; tokens/s reported only (cpu: "
                         "virtual-device mesh)"),
                "tp_ranks": 2, "groups": 2,
                "offered_load": {"concurrency": concurrency,
                                 "requests_per_sweep": n_requests},
                "steady": steady, "swap_sweep": swap,
                "kill": kill_info,
                "group1_actions": acts,
                "no_drop_ok": bool(no_drop),
                "all_responded_ok": bool(all_responded),
                "die_as_unit_chain_ok": bool(chain_ok),
                "restarted_group_serves_ok": bool(restarted_serves),
                "hot_swaps_observed": swaps,
                "shard_verify_records": shard_verified,
                "serving_violations": [v.to_dict() for v in violations],
                "serve_group_violations": [v.to_dict()
                                           for v in group_violations],
                **_env_stamp()}}
    finally:
        for p in supervisors:
            try:
                if p.poll() is None:
                    p.kill()
            except Exception:
                pass
        shutil.rmtree(workdir, ignore_errors=True)


def bench_input_pipeline_overlap() -> dict:
    """Dispatch-ahead input pipeline: a deliberately slow host loader
    feeding the flagship CNN step, sync-feed (next → device_put →
    dispatch → drain, serial) vs prefetch-feed (DevicePrefetcher at the
    production depth). The loader's per-batch cost is calibrated to the
    measured step wall, so a working overlap reads ~2× and the gate is
    ≥ 1.5× batches/sec. The consumer drains every step — the shape
    where the host's serial feed is fully exposed (and what a
    metrics-hungry policy loop looks like); the interleaved-repeat
    median gates it, as in bench_mode_overhead."""
    from distributedmnist_tpu.core.config import DataConfig
    from distributedmnist_tpu.data.datasets import make_synthetic
    from distributedmnist_tpu.data.device_prefetch import DevicePrefetcher

    n_dev = len(jax.devices())
    # the gate is a RATIO of feed disciplines, not a throughput anchor:
    # keep the step light on CPU meshes (8 virtual devices over a
    # couple of real cores turn a big conv step into multi-second
    # rendezvous), full-size on a real accelerator
    per_dev = 64 if jax.default_backend() == "cpu" else 2048
    batch = per_dev * max(1, n_dev)
    cfg, topo, model, state, step_fn = _build({
        "data": {"dataset": "synthetic", "batch_size": batch},
        "model": {"compute_dtype": "bfloat16"},
        "sync": {"mode": "sync"},
    })
    ds = make_synthetic(num_train=batch, num_test=64)
    host_batch = {"image": ds.train.images[:batch],
                  "label": ds.train.labels[:batch]}

    # compile + warm, then calibrate the per-step wall (dispatch +
    # drain) the slow loader is matched against
    state, m = step_fn(state, topo.device_put_batch(host_batch))
    _drain(m)
    calib = []
    for _ in range(5):
        t0 = time.perf_counter()
        state, m = step_fn(state, topo.device_put_batch(host_batch))
        float(m["loss"])
        calib.append(time.perf_counter() - t0)
    exec_s = statistics.median(calib)
    # loader cost ≈ step cost maximizes the visible overlap (expected
    # ~2×); the floor keeps sleep() resolution out of the measurement
    sleep_s = max(exec_s, 0.002)

    class SlowLoader:
        """Stand-in for an expensive host stage (decode / augment /
        assembly): sleep-dominated, so the cost is overlappable
        wherever a producer thread can run — exactly what the
        prefetcher must exploit."""

        def __iter__(self):
            return self

        def __next__(self):
            time.sleep(sleep_s)
            return host_batch

    depth = DataConfig().device_prefetch_depth
    n_batches, n_repeats = 12, 3

    def run_arm(prefetched: bool, st):
        loader = SlowLoader()
        feed = (DevicePrefetcher(loader, put=topo.device_put_batch,
                                 depth=depth) if prefetched else None)
        try:
            t0 = time.perf_counter()
            for _ in range(n_batches):
                g = next(feed) if prefetched else topo.device_put_batch(
                    next(loader))
                st, m = step_fn(st, g)
                float(m["loss"])  # drain: expose the feed fully
            dt = time.perf_counter() - t0
        finally:
            if feed is not None:
                feed.close()
        return n_batches / dt, st

    rates: dict[str, list[float]] = {"sync": [], "prefetch": []}
    for _ in range(n_repeats):  # interleaved: drift lands on both arms
        for name, pf in (("sync", False), ("prefetch", True)):
            rate, state = run_arm(pf, state)
            rates[name].append(rate)

    med = {k: statistics.median(v) for k, v in rates.items()}
    speedup = med["prefetch"] / med["sync"]
    return {
        "metric": "input_pipeline_overlap_speedup",
        "value": round(speedup, 2), "unit": "x (prefetch/sync batches/sec)",
        "meets_1p5x_gate": bool(speedup >= 1.5),
        "detail": {
            "gate": f"median of {n_repeats} interleaved repeats ≥ 1.5x",
            "step_wall_ms": round(exec_s * 1e3, 2),
            "loader_ms_per_batch": round(sleep_s * 1e3, 2),
            "prefetch_depth": depth, "batch": batch,
            "batches_per_sec": {k: [round(r, 2) for r in v]
                                for k, v in rates.items()},
            "expected_upper_bound_x": round(
                (sleep_s + exec_s) / max(sleep_s, exec_s), 2),
            **_env_stamp()}}


def bench_autoscale_response() -> dict:
    """Resource broker (ISSUE 16), gated in one process: a BROKERED
    roster beats a STATIC allocation of the same device budget under
    the same burst, and the detect→capacity-live reaction time is
    measured, not assumed.

    The budget is three slots. The static arm pins one serving replica
    and leaves two with the (notional) trainer for the whole burst —
    sixteen closed-loop clients against a queue_depth-4 admission
    bound. The replica sheds overload as typed ``overloaded`` rejects,
    and the client's failover shim retries those; with max_attempts=1
    the retry budget is spent immediately and every shed lands as a
    terminal ``error:unavailable`` outcome — the typed refusal the
    gate counts. Pressure therefore surfaces to the broker as queue-
    wait latency (the window's p99), which is exactly what the p99
    threshold marks exist for. The brokered arm starts identically,
    but the real decision core (:func:`launch.broker.decide`) watches
    the loadgen's journaled rolling window; the first p99 crossing
    trades a trainer slot for a second live ServingReplica (capacity
    live = it answers meta), and the remaining burst spreads across
    both. Gate: the scale-up actually fired, zero SILENT drops in
    either arm (typed refusals are admission control, not drops), and
    the brokered arm refuses measurably less (rejected+errors <= 0.8x
    static; if the static arm never shed at all, brokered p99 must
    not be worse than 1.1x static — the budget trade can't have
    hurt)."""
    import shutil
    import tempfile
    import threading
    from pathlib import Path

    from distributedmnist_tpu.core.config import (BrokerConfig,
                                                  ExperimentConfig,
                                                  ServeConfig)
    from distributedmnist_tpu.launch.broker import (SCALE_UP,
                                                    collect_signals,
                                                    decide)
    from distributedmnist_tpu.servesvc.client import ServeClient
    from distributedmnist_tpu.servesvc.loadgen import (make_input_fn,
                                                       read_latest_window,
                                                       run_load)
    from distributedmnist_tpu.servesvc.server import ServingReplica
    from distributedmnist_tpu.train.loop import Trainer

    workdir = Path(tempfile.mkdtemp(prefix="dmt_autoscale_bench_"))
    publish = workdir / "publish"
    concurrency, n_requests = 16, 2000
    scfg = ServeConfig(poll_secs=0.5, queue_depth=4, max_batch=8,
                       default_deadline_ms=10_000.0)
    # p99 marks are the live trigger: one pressured replica queues
    # requests to ~200ms p99 (measured: conc 16 vs queue_depth 4),
    # calm sits well under 120. The reject marks stay as a secondary
    # trip-wire but can't fire here — the client retries typed
    # ``overloaded`` rejects, so the window's reject_rate (terminal
    # status=="rejected" only) stays 0 under pure overload.
    bcfg = BrokerConfig(window_s=2.0, cooldown_s=5.0,
                        reject_high=0.05, reject_low=0.005,
                        p99_high_ms=120.0, p99_low_ms=40.0,
                        max_serve_replicas=2, max_train_workers=2,
                        settle_timeout_s=30.0)
    replicas: list = []

    def spawn(name: str) -> "ServingReplica":
        r = ServingReplica(publish, serve_dir=workdir / name, scfg=scfg,
                           cfg=cfg)
        r.start()
        replicas.append(r)
        return r

    try:
        # stage one published checkpoint (a short deterministic run)
        cfg = ExperimentConfig().override({
            "data.dataset": "synthetic", "data.batch_size": 32,
            "data.synthetic_train_size": 256,
            "data.synthetic_test_size": 64,
            "model.compute_dtype": "float32", "train.max_steps": 10,
            "train.train_dir": str(publish),
            "train.log_every_steps": 10,
            "train.save_interval_steps": 10,
            "train.async_checkpoint": False,
            "train.save_results_period": 0})
        Trainer(cfg).run()

        r1 = spawn("replica1")
        endpoints = [("127.0.0.1", r1.bound_port)]
        # max_attempts=1: the failover shim always retries typed
        # ``overloaded`` rejects, so a shed can never come back as
        # terminal status=="rejected" — with one attempt the budget
        # exhausts on the spot and the shed lands as a countable
        # terminal ``error:unavailable`` instead of being smeared
        # into retry latency
        client = ServeClient(lambda: list(endpoints), deadline_s=10.0,
                             max_attempts=1)
        make_input = make_input_fn(list(r1.model.input_shape),
                                   str(np.dtype(r1.model.input_dtype)))
        # warm the bucket shapes once so neither arm pays r1's compile
        run_load(client, 4, 1, make_input)
        run_load(client, 4 * concurrency, concurrency, make_input)

        # -- static arm: 1 replica holds the whole burst ----------------
        static = run_load(client, n_requests, concurrency, make_input,
                          journal_path=workdir / "loadgen_static.jsonl")

        # -- brokered arm: decide() on the live window ------------------
        journal = workdir / "loadgen_brokered.jsonl"
        reaction: dict = {}
        stop_mon = threading.Event()

        def monitor() -> None:
            # the broker loop, minus the process tree: 1 serving slot
            # + 2 train slots; the first crossing trades train->serve
            while not stop_mon.is_set():
                now = time.time()
                sig = collect_signals(read_latest_window(journal), [],
                                      now=now, window_s=bcfg.window_s)
                d = decide(bcfg, 1, 2, sig, None, now)
                if d is not None and d.decision == SCALE_UP:
                    reaction["t_detect"] = now
                    reaction["trigger"] = d.trigger
                    reaction["value"] = d.value
                    r2 = spawn("replica2")
                    probe = ServeClient([("127.0.0.1", r2.bound_port)],
                                        deadline_s=1.0)
                    while probe.meta(deadline_s=1.0) is None \
                            and not stop_mon.is_set():
                        time.sleep(0.05)
                    reaction["t_live"] = time.time()
                    reaction["reaction_s"] = round(
                        reaction["t_live"] - reaction["t_detect"], 3)
                    endpoints.append(("127.0.0.1", r2.bound_port))
                    return
                time.sleep(0.1)

        mon = threading.Thread(target=monitor, daemon=True)
        mon.start()
        brokered = run_load(client, n_requests, concurrency, make_input,
                            journal_path=journal, window_s=bcfg.window_s,
                            snapshot_every_s=0.5)
        stop_mon.set()
        mon.join(timeout=10)

        fired = "reaction_s" in reaction
        # dropped = issued but never resolved (a silent loss); typed
        # refusals (rejected / error:unavailable) are admission
        # control doing its job and are judged by the shed gate below
        no_drop = (static["dropped"] == 0 and brokered["dropped"] == 0)
        static_shed = static["rejected"] + static["errors"]
        brokered_shed = brokered["rejected"] + brokered["errors"]
        if static_shed > 0:
            shed_ok = brokered_shed <= 0.8 * static_shed
            gate_how = ("brokered typed refusals (rejected+errors) "
                        "<= 0.8x static")
        else:
            shed_ok = (brokered["latency_ms"]["p99"]
                       <= 1.1 * static["latency_ms"]["p99"])
            gate_how = ("static never shed: brokered p99 <= 1.1x "
                        "static p99")
        passes = bool(fired and no_drop and shed_ok)
        return {
            "metric": "autoscale_response",
            "value": reaction.get("reaction_s"),
            "unit": "s detect->capacity-live",
            "passes_gate": passes,
            "detail": {
                "gate": ("scale-up fired AND zero silent drops in "
                         "both arms AND " + gate_how),
                "budget": {"slots": 3, "static": "1 serve + 2 train",
                           "brokered": "1->2 serve"},
                "offered_load": {"concurrency": concurrency,
                                 "requests_per_arm": n_requests},
                "static": static, "brokered": brokered,
                "reaction": reaction,
                "fired_ok": bool(fired), "no_drop_ok": bool(no_drop),
                "shed_ok": bool(shed_ok),
                "shed_static": static_shed,
                "shed_brokered": brokered_shed,
                **_env_stamp()}}
    finally:
        for r in replicas:
            try:
                r.stop()
            except Exception:
                pass
        shutil.rmtree(workdir, ignore_errors=True)


def bench_straggler_adaptation() -> dict:
    """Online straggler-discipline controller (ISSUE 18), gated: under
    a phased straggler schedule the ADAPTIVE quorum discipline reaches
    the target step count in less modeled wall time than the best
    STATIC discipline an operator could have tuned a priori — with the
    per-window discipline trace journaled and zero flaps.

    The schedule is seeded and phased: calm (all four replicas near
    50 ms) → two-of-four stragglers at 8× → a uniform 3× slowdown
    (every replica healthy but slow — the phase that breaks any fixed
    deadline). The adaptive arm runs the REAL jitted quorum step with
    the schedule injected through the traced ``measured_ms`` input and
    the live ``[k, timeout_ms, interval_ms]`` discipline vector — the
    tentpole claim measured, not assumed: the controller's swaps change
    which replicas the emitted flags mask with ONE compiled executable
    (cache size asserted). Per-step barrier cost is the slowest
    CONTRIBUTING replica's time, read from the emitted flags.

    Static arms (modeled on the same schedule): sync (wait for all),
    quorum k=n-1 (the paper's backup-worker recipe, arXiv:1604.00981),
    and a timeout tuned the only way a static deadline honestly can be
    — generous against the tail observed BEFORE deployment (1.5x the
    calm phase's p99). That deadline masks the 8x stragglers nicely,
    then masks EVERY replica in the uniform-slowdown phase: zero
    contributors, zero progress — the failure mode that motivates
    retargeting the deadline from the live p50 instead of a frozen one.
    An arm that never applies its target number of updates does not
    complete, and is excluded from (but reported next to) the margin.

    Gate: adaptive completes, beats the best completing static by
    >= 10% on modeled time-to-target, adapted in BOTH directions
    (>= 1 tighten and >= 1 relax journaled + licensed), with zero
    flaps. Honest skip (< 4 devices realizable): the pure decision
    core replays the same schedule's CDFs — the decision trace is
    still asserted both directions, but no timing gate is claimed."""
    from distributedmnist_tpu.core.config import MeshConfig
    from distributedmnist_tpu.core.mesh import make_topology
    from distributedmnist_tpu.train.discipline import (
        DisciplineController, WindowStats, discipline_trace)

    n = 4
    base, spike, slow = 50.0, 8.0, 3.0
    phases = (("calm", 25, np.ones(n)),
              ("stragglers_2of4", 30,
               np.array([1.0, 1.0, spike, spike])),
              ("uniform_slow", 25, np.full(n, slow)))
    rng = np.random.default_rng(0)
    rows, phase_of = [], []
    for name, steps, mult in phases:
        for _ in range(steps):
            rows.append(base * mult + rng.uniform(0.0, 1.5, n))
            phase_of.append(name)
    times = np.stack(rows)          # [steps, n] the ground-truth CDF
    total_steps = times.shape[0]
    window, cooldown = 6, 6

    sync_cfg = {"mode": "quorum", "adaptive": True,
                "adaptive_window_steps": window,
                "adaptive_cooldown_steps": cooldown}

    def static_cost(t_row: np.ndarray, kind: str, k: int = n,
                    deadline: float = 0.0) -> tuple[float, int]:
        """(modeled barrier seconds-equivalent ms, contributors)."""
        s = np.sort(t_row)
        if kind == "quorum":
            return float(s[k - 1]), k
        mask = t_row <= deadline
        if not mask.any():
            return deadline, 0     # waited the deadline out for nothing
        return (float(t_row.max()) if mask.all()
                else deadline), int(mask.sum())

    def run_static(kind: str, k: int = n, deadline: float = 0.0) -> dict:
        cost = applied = 0.0
        for i in range(total_steps):
            c, m = static_cost(times[i], kind, k, deadline)
            cost += c
            applied += 1 if m > 0 else 0
        return {"time_ms": round(cost, 1), "applied": int(applied),
                "completed": applied == total_steps}

    calm = times[:phases[0][1]]
    static_deadline = round(1.5 * float(np.percentile(calm, 99)), 1)
    statics = {
        "sync": run_static("quorum", k=n),
        "quorum_k3": run_static("quorum", k=n - 1),
        f"timeout_{static_deadline}ms": run_static(
            "timeout", deadline=static_deadline)}

    journal: list[dict] = []
    from distributedmnist_tpu.core.config import ExperimentConfig
    scfg = ExperimentConfig.from_dict({"sync": sync_cfg}).sync

    def window_stats(history: list[np.ndarray]) -> WindowStats | None:
        if len(history) < window:
            return None
        tail = np.stack(history[-window:])
        p50, p90, p99 = np.percentile(tail, (50.0, 90.0, 99.0))
        fast = float(np.median(tail, axis=0).min())
        return WindowStats(p50_ms=float(p50), p90_ms=float(p90),
                           p99_ms=float(p99), n_samples=window,
                           fast_p50_ms=fast)

    cache_size = None
    try:
        # must land BEFORE the first backend touch — this case runs in
        # its own CI step (DMT_BENCH_CASES=straggler_adaptation) so it
        # owns the process's jax init
        from distributedmnist_tpu.core.mesh import simulate_devices
        simulate_devices(n)
        topo = make_topology(MeshConfig(simulate_devices=n))
        realizable = topo.num_replicas >= n
    except Exception as e:  # backend already pinned to fewer devices
        realizable, topo = False, None
        print(f"# straggler_adaptation: no {n}-device mesh: {e}",
              file=sys.stderr)

    if realizable:
        from distributedmnist_tpu.parallel.api import make_discipline_vector
        cfg, topo, model, state, step_fn = _build({
            "data": {"dataset": "synthetic", "batch_size": 32},
            "model": {"compute_dtype": "float32"},
            "sync": sync_cfg,
        }, topo)
        from distributedmnist_tpu.data.datasets import make_synthetic
        ds = make_synthetic(num_train=32, num_test=16)
        gbatch = topo.device_put_batch({"image": ds.train.images[:32],
                                        "label": ds.train.labels[:32]})
        ctrl = DisciplineController(scfg, n, journal.append,
                                    make_discipline_vector)
        cost = 0.0
        history: list[np.ndarray] = []
        for i in range(total_steps):
            measured = topo.device_put_measured(times[i])
            state, metrics = step_fn(state, gbatch, measured,
                                     ctrl.vector)
            t = np.asarray(metrics["step_times_ms"], dtype=np.float64)
            flags = np.asarray(metrics["flags"])
            cost += float(t[flags > 0].max())
            history.append(t)
            ctrl.maybe_adapt(i + 1, window_stats(history))
        adaptive = {"time_ms": round(cost, 1), "applied": total_steps,
                    "completed": True}
        try:
            cache_size = int(step_fn.jitted._cache_size())
        except Exception:
            cache_size = None
    else:
        # honest skip: the pure decision core over the same schedule —
        # asserts the controller's trace, claims nothing about timing
        ctrl = DisciplineController(
            scfg, n, journal.append,
            lambda k, t_ms, i_ms: (k, t_ms, i_ms))
        cost = 0.0
        history = []
        for i in range(total_steps):
            k = int(ctrl.current.k)
            c, _ = static_cost(times[i], "quorum", k)
            cost += c
            history.append(times[i])
            ctrl.maybe_adapt(i + 1, window_stats(history))
        adaptive = {"time_ms": round(cost, 1), "applied": total_steps,
                    "completed": True, "modeled_only": True}

    summary = ctrl.summary()
    trace = discipline_trace(journal)
    decisions = [r.get("decision") for r in journal
                 if r.get("action") == "begin"]
    tightens = sum(1 for d in decisions if str(d).startswith("tighten"))
    relaxes = len(decisions) - tightens
    from distributedmnist_tpu.obsv.journal import summarize_discipline
    disc = summarize_discipline(journal)
    completing = {k: v for k, v in statics.items() if v["completed"]}
    best_name = min(completing, key=lambda k: completing[k]["time_ms"])
    best = completing[best_name]["time_ms"]
    margin = round(1.0 - adaptive["time_ms"] / best, 3) if best else None
    both_ways = tightens >= 1 and relaxes >= 1
    if realizable:
        passes = bool(adaptive["completed"] and margin is not None
                      and margin >= 0.10 and both_ways
                      and disc["flaps"] == 0
                      and (cache_size is None or cache_size == 1))
        skipped = None
    else:
        passes = None
        skipped = (f"fewer than {n} devices realizable: the traced "
                   "timing signal cannot run; decision trace asserted "
                   "on the modeled CDF instead "
                   f"(both_ways={both_ways}, flaps={disc['flaps']})")
        if not (both_ways and disc["flaps"] == 0):
            passes = False  # even the modeled trace misbehaved
    print(f"# straggler_adaptation: adaptive={adaptive['time_ms']}ms "
          f"best_static={best_name}:{best}ms margin={margin} "
          f"changes={summary['changes']} trace={trace} "
          f"jit_cache={cache_size}", file=sys.stderr)
    return {
        "metric": "straggler_adaptation_margin",
        "value": margin,
        "unit": "fraction vs best completing static",
        "passes_gate": passes,
        "detail": {
            "gate": ("adaptive completes AND beats best completing "
                     "static by >= 10% modeled time-to-target AND "
                     ">=1 tighten AND >=1 relax AND zero flaps AND "
                     "one compiled executable across swaps"),
            "schedule": [{"phase": p[0], "steps": p[1],
                          "multipliers": list(map(float, p[2]))}
                         for p in phases],
            "static_deadline_ms": static_deadline,
            "adaptive": adaptive, "statics": statics,
            "best_static": best_name,
            "discipline": {"changes": summary["changes"],
                           "tightens": tightens, "relaxes": relaxes,
                           "flaps": disc["flaps"], "trace": trace},
            "jit_cache_size": cache_size,
            **({"skipped": skipped} if skipped else {}),
            **_env_stamp()}}


def main() -> None:
    """Run every case, then print the ONE self-contained artifact line
    on stdout, LAST — the driver keeps the tail of the output, so
    last-wins is what makes the artifact survive capture (VERDICT weak
    #2: headline-first + cases-on-stderr lost the cnn headline).

    ``DMT_BENCH_CASES`` (comma-separated substrings of case-function
    names) selects a subset — what lets CI afford an artifact on CPU
    runners, where the full flash/pallas cases are minutes-scale. The
    artifact notes the filter so a subset can never pass for a full run.
    """
    import os

    only = {s.strip() for s in os.environ.get("DMT_BENCH_CASES",
                                              "").split(",") if s.strip()}

    def want(fn) -> bool:
        return not only or any(k in fn.__name__ for k in only)

    if want(bench_cnn_sync):
        headline = bench_cnn_sync()
        _case(headline)  # stderr progress; stdout reserved for the end
    else:
        headline = {"metric": "bench_subset", "value": None, "unit": None,
                    "vs_baseline": None,
                    "subset": sorted(only)}
    cases: list[dict] = []
    for case in (bench_transformer_flash, bench_flash_long_context,
                 bench_mode_overhead, bench_native_loader,
                 bench_input_pipeline_overlap, bench_weight_update_sharding,
                 bench_zero1_overlap, bench_save_stall,
                 bench_checkpoint_durability,
                 bench_weak_scaling, bench_restart_latency,
                 bench_serving_latency, bench_degraded_network,
                 bench_quantized_serving,
                 bench_decode_throughput, bench_tp_serving,
                 bench_autoscale_response, bench_straggler_adaptation):
        if not want(case):
            continue
        try:
            got = case()
        except Exception as e:  # a failed case must not kill the headline
            got = {"metric": case.__name__,
                   "error": f"{type(e).__name__}: {e}"}
        for record in got if isinstance(got, list) else [got]:
            _case(record)
            cases.append(record)
    # regression guard: the headline ratchets (every case carrying a
    # vs_baseline anchor: CNN, transformer flash, long-context flash)
    # must not move down while the overlap case moves up (ISSUE 2
    # acceptance) — surfaced as one field instead of leaving the
    # reader to scan cases. `ok` is vs the PUBLISHED round-1 anchor
    # (the repo's ratchet mechanism); round-over-round trajectory
    # lives in the BENCH_r* history, not here.
    anchored_fns = ("bench_transformer_flash", "bench_flash_long_context")
    guarded = [headline] + [
        c for c in cases
        # a CRASHED anchor case records {"metric": fn_name, "error":..}
        # with no vs_baseline — it must appear here as not-ok, not
        # silently vanish from the guard
        if "vs_baseline" in c or c.get("metric") in anchored_fns]
    guard = {
        "threshold": "vs_baseline >= 0.9 of the published anchor",
        "cases": [{"metric": c.get("metric"),
                   "vs_baseline": c.get("vs_baseline"),
                   "ok": (False if "error" in c
                          else None if c.get("vs_baseline") is None
                          else bool(c["vs_baseline"] >= 0.9))}
                  for c in guarded]}
    # compile time as a first-class artifact metric (ROADMAP item 5):
    # every case already measures its compile_s — surface them in one
    # place, headline_regression_guard-style, so a compile-cache or
    # lowering regression shows up in the bench JSON trajectory
    # instead of hiding inside per-case detail
    compile_seconds = {
        "note": ("per-case XLA compile wall seconds; compare across "
                 "BENCH_r* rounds — a jump here is a compile/lowering "
                 "or persistent-cache regression even when throughput "
                 "holds"),
        "by_case": {c.get("metric"): c["detail"]["compile_s"]
                    for c in [headline] + cases
                    if isinstance(c.get("detail"), dict)
                    and c["detail"].get("compile_s") is not None}}
    print(json.dumps({**headline, "cases": cases,
                      "headline_regression_guard": guard,
                      "compile_seconds": compile_seconds},
                     separators=(",", ":")))


if __name__ == "__main__":
    main()
