"""Repo-root campaign entry point: force the 8-virtual-device CPU mesh
BEFORE any backend init, then hand off to the packaged campaign driver
(distributedmnist_tpu/launch/campaign.py — see its docstring for what
the campaign runs and why). Also reachable as
``python -m distributedmnist_tpu.launch campaign``."""

from __future__ import annotations

import sys
from pathlib import Path

from distributedmnist_tpu.core.mesh import simulate_devices

simulate_devices(8)  # before any backend init

from distributedmnist_tpu.launch.campaign import (  # noqa: E402,F401
    EVALUATED_RUN, GROUPS, OVERRIDES, finalize, main, prune_heavy_artifacts,
    run_group, start_evaluator, stop_evaluator)

if __name__ == "__main__":
    sys.exit(main(root=Path(__file__).parent))
